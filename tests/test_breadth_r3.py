"""Round-3 breadth families: detection, sequence, train ops, transforms,
sparse zoo, viterbi, fused incubate ops, registry/zoo size gates.

Reference analog: the per-op test_*_op.py files of test/legacy_test
(SURVEY.md §4) — numpy-reference checks per family; the size gates pin
the VERDICT r2 item-3 targets (>=800 registry ops, >=160 Layer classes).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle


class TestRegistrySize:
    def test_at_least_800_ops(self):
        from paddle_tpu.ops._registry import REGISTRY
        assert len(REGISTRY) >= 800, len(REGISTRY)

    def test_at_least_160_layers_across_zoo(self):
        import inspect
        import paddle_tpu.nn as nn
        import paddle_tpu.incubate.nn as inn
        import paddle_tpu.sparse.nn as snn
        import paddle_tpu.distributed.fleet.mpu as mpu
        import paddle_tpu.audio.features as af
        import paddle_tpu.quantization as q
        seen = set()
        total = 0
        for m in (nn, inn, snn, mpu, af, q):
            for name in dir(m):
                o = getattr(m, name, None)
                if (inspect.isclass(o) and issubclass(o, nn.Layer)
                        and o is not nn.Layer and id(o) not in seen):
                    seen.add(id(o))
                    total += 1
        assert total >= 160, total


class TestDetectionOps:
    def test_iou_identity(self):
        b = paddle.to_tensor(np.array([[0., 0., 2., 2.], [1., 1., 3., 3.]],
                                      np.float32))
        iou = paddle.iou_similarity(b, b).numpy()
        np.testing.assert_allclose(np.diag(iou), [1.0, 1.0], rtol=1e-6)
        assert abs(iou[0, 1] - 2.0 / 14.0) < 1e-6  # inter 1, union 7... 4+4-1

    def test_box_clip(self):
        boxes = paddle.to_tensor(np.array([[-5., -5., 50., 50.]], np.float32))
        out = paddle.box_clip(boxes, paddle.to_tensor(
            np.array([20., 30., 1.], np.float32))).numpy()
        np.testing.assert_allclose(out, [[0., 0., 29., 19.]])

    def test_box_coder_roundtrip(self):
        rng = np.random.RandomState(0)
        priors = paddle.to_tensor(
            np.abs(rng.rand(5, 4)).cumsum(axis=1).astype(np.float32))
        targets = paddle.to_tensor(
            np.abs(rng.rand(5, 4)).cumsum(axis=1).astype(np.float32) * 2)
        enc = paddle.vision.ops.box_coder(priors, None, targets,
                                          code_type="encode_center_size")
        dec = paddle.vision.ops.box_coder(priors, None, enc,
                                          code_type="decode_center_size",
                                          axis=0)
        got = dec.numpy()[np.arange(5), np.arange(5)]
        np.testing.assert_allclose(got, targets.numpy(), rtol=1e-4,
                                   atol=1e-4)

    def test_multiclass_nms_suppresses_overlaps(self):
        boxes = np.zeros((1, 4, 4), np.float32)
        boxes[0, 0] = [0, 0, 10, 10]
        boxes[0, 1] = [0.5, 0.5, 10.5, 10.5]   # heavy overlap with 0
        boxes[0, 2] = [20, 20, 30, 30]
        boxes[0, 3] = [40, 40, 50, 50]
        scores = np.zeros((1, 1, 4), np.float32)
        scores[0, 0] = [0.9, 0.8, 0.7, 0.6]
        out, num = paddle.vision.ops.multiclass_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.1, keep_top_k=4, nms_threshold=0.5)
        assert int(num.numpy()[0]) == 3  # box 1 suppressed

    def test_matrix_nms_decays(self):
        boxes = np.zeros((1, 3, 4), np.float32)
        boxes[0, 0] = [0, 0, 10, 10]
        boxes[0, 1] = [0, 0, 10, 10]
        boxes[0, 2] = [20, 20, 30, 30]
        scores = np.zeros((1, 1, 3), np.float32)
        scores[0, 0] = [0.9, 0.8, 0.7]
        out, num = paddle.vision.ops.matrix_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.0, nms_top_k=3,
            keep_top_k=3)
        s = out.numpy()[0][:, 1]
        assert s[0] > 0.89 and s[2] < 0.1  # duplicate decayed to ~0


class TestSequenceOps:
    def test_pool_and_softmax_respect_lengths(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(2, 3, 2))
        ln = paddle.to_tensor(np.array([2, 3]))
        avg = paddle.sequence_pool(x, ln, "average").numpy()
        np.testing.assert_allclose(avg[0], [1.0, 2.0])  # mean of rows 0,1
        sm = paddle.sequence_softmax(x, ln).numpy()
        np.testing.assert_allclose(sm[0].sum(0), [1.0, 1.0], rtol=1e-5)
        assert sm[0, 2].sum() == 0  # padded step zeroed

    def test_reverse_valid_prefix(self):
        x = paddle.to_tensor(np.array([[1., 2., 3., 9.]]).reshape(1, 4, 1))
        out = paddle.sequence_reverse(
            x, paddle.to_tensor(np.array([3]))).numpy().reshape(-1)
        np.testing.assert_allclose(out, [3., 2., 1., 9.])

    def test_sequence_conv_shapes(self):
        x = paddle.to_tensor(np.random.randn(2, 5, 3).astype(np.float32))
        f = paddle.to_tensor(np.random.randn(9, 4).astype(np.float32))
        out = paddle.sequence_conv(x, paddle.to_tensor(np.array([5, 2])), f)
        assert out.shape == [2, 5, 4]
        assert np.all(out.numpy()[1, 2:] == 0)  # masked beyond length


class TestTrainOps:
    def test_adam_matches_reference_formula(self):
        p = paddle.to_tensor(np.ones((4,), np.float32))
        g = paddle.to_tensor(np.full((4,), 0.5, np.float32))
        m = paddle.to_tensor(np.zeros((4,), np.float32))
        v = paddle.to_tensor(np.zeros((4,), np.float32))
        step = paddle.to_tensor(np.ones((), np.int64))
        p2, m2, v2, s2 = paddle.adam_(p, g, m, v, step, learning_rate=0.1)
        # first step: mhat = g, vhat = g^2 -> p - lr*g/(|g|+eps) ~= p - lr
        np.testing.assert_allclose(p2.numpy(), 1.0 - 0.1, rtol=1e-4)

    def test_check_finite_and_unscale(self):
        gs = [paddle.to_tensor(np.array([2.0, 4.0], np.float32)),
              paddle.to_tensor(np.array([np.inf], np.float32))]
        outs, found = paddle.check_finite_and_unscale(
            gs, paddle.to_tensor(np.array(2.0, np.float32)))
        np.testing.assert_allclose(outs[0].numpy(), [1.0, 2.0])
        assert bool(found.numpy()[0])

    def test_update_loss_scaling_shrinks_on_inf(self):
        s, good, bad = (paddle.to_tensor(np.array(1024.0, np.float32)),
                        paddle.to_tensor(np.array(5, np.int32)),
                        paddle.to_tensor(np.array(1, np.int32)))
        inf = paddle.to_tensor(np.array([True]))
        s2, g2, b2 = paddle.update_loss_scaling(
            s, good, bad, inf, decr_every_n_nan_or_inf=2)
        assert float(s2.numpy()) == 512.0


class TestTransformsFunctional:
    def test_flips_and_identity_affine(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.RandomState(0).rand(6, 8, 3) * 255).astype(np.uint8)
        np.testing.assert_array_equal(T.hflip(T.hflip(img)), img)
        np.testing.assert_array_equal(T.vflip(T.vflip(img)), img)
        np.testing.assert_array_equal(
            T.affine(img, 0, (0, 0), 1.0, 0), img)
        np.testing.assert_array_equal(T.rotate(img, 0), img)

    def test_adjusts(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.RandomState(1).rand(6, 8, 3)).astype(np.float32)
        np.testing.assert_allclose(T.adjust_brightness(img, 1.0), img,
                                   rtol=1e-6)
        np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=1e-5)
        gray = T.to_grayscale(img)
        assert gray.shape == (6, 8, 1)


class TestViterbi:
    def test_decode_prefers_high_potentials(self):
        import paddle_tpu.text as text
        pot = np.full((1, 4, 3), -10.0, np.float32)
        best = [0, 2, 1, 0]
        for t, tag in enumerate(best):
            pot[0, t, tag] = 10.0
        scores, path = text.viterbi_decode(
            paddle.to_tensor(pot),
            paddle.to_tensor(np.zeros((3, 3), np.float32)),
            paddle.to_tensor(np.array([4])), False)
        assert path.numpy()[0].tolist() == best


class TestSparseZoo:
    def test_unary_zoo_values_only(self):
        import paddle_tpu.sparse as sp
        st = sp.sparse_coo_tensor([[0, 1], [1, 0]], [0.5, -0.25], [2, 2])
        out = sp.asin(st).to_dense().numpy()
        assert abs(out[0, 1] - np.arcsin(0.5)) < 1e-6
        assert out[0, 0] == 0.0
        assert sp.expm1(st).nnz == 2

    def test_sparse_nn_layers(self):
        import paddle_tpu.sparse as sp
        from jax.experimental import sparse as jsp
        dense = np.zeros((1, 3, 3, 3, 2), np.float32)
        dense[0, 1, 1, 1] = [1.0, -2.0]
        xs = sp.SparseCooTensor(jsp.BCOO.fromdense(jnp.asarray(dense)))
        out = sp.nn.ReLU()(xs).to_dense().numpy()
        assert out[0, 1, 1, 1, 0] == 1.0 and out[0, 1, 1, 1, 1] == 0.0
        conv = sp.nn.SubmConv3D(2, 4, 3, padding=1)
        y = conv(xs)
        # submanifold: output active only at the input's active site
        yd = y.to_dense().numpy()
        assert np.all(yd[0, 0, 0, 0] == 0)

    def test_mask_as(self):
        import paddle_tpu.sparse as sp
        st = sp.sparse_coo_tensor([[0, 1], [1, 0]], [1.0, 1.0], [2, 2])
        dense = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
        out = sp.mask_as(dense, st).to_dense().numpy()
        np.testing.assert_allclose(out, [[0., 1.], [2., 0.]])


class TestFusedIncubate:
    def test_swiglu_split(self):
        import paddle_tpu.incubate.nn.functional as inf
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        out = inf.swiglu(x).numpy()
        a, b = x.numpy()[:, :4], x.numpy()[:, 4:]
        ref = (a / (1 + np.exp(-a))) * b
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_fused_ec_moe_single_expert_is_mlp(self):
        import paddle_tpu.incubate.nn.functional as inf
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(1, 3, 4).astype(np.float32))
        gate = paddle.to_tensor(np.zeros((1, 3, 1), np.float32))
        w0 = paddle.to_tensor(rng.randn(1, 4, 8).astype(np.float32) * 0.1)
        b0 = paddle.to_tensor(np.zeros((1, 1, 8), np.float32))
        w1 = paddle.to_tensor(rng.randn(1, 8, 4).astype(np.float32) * 0.1)
        b1 = paddle.to_tensor(np.zeros((1, 1, 4), np.float32))
        out = inf.fused_ec_moe(x, gate, w0, b0, w1, b1).numpy()
        # single expert, uniform gate -> plain gelu MLP
        h = x.numpy() @ w0.numpy()[0]
        h = 0.5 * h * (1 + np.vectorize(np.math.erf if hasattr(np, "math")
                                        else __import__("math").erf)(
            h / np.sqrt(2.0)))
        ref = h @ w1.numpy()[0]
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)

    def test_masked_mha_appends(self):
        import paddle_tpu.incubate.nn.functional as inf
        x = paddle.to_tensor(np.random.randn(1, 3 * 2 * 4).astype(
            np.float32))
        cache = paddle.to_tensor(np.zeros((2, 1, 2, 4, 4), np.float32))
        out, newc = inf.masked_multihead_attention(x, cache)
        assert out.shape == [1, 8]
        assert np.any(newc.numpy()[0, 0, :, 0] != 0)   # slot 0 filled


class TestQuantOps:
    def test_fake_quant_roundtrip_small_error(self):
        import paddle_tpu.quantization as q
        x = paddle.to_tensor(np.random.RandomState(0).randn(16).astype(
            np.float32))
        out, scale = q.fake_quantize_abs_max(x)
        assert np.max(np.abs(out.numpy() - x.numpy())) < \
            float(scale.numpy()[0]) / 100

    def test_quant_dequant_linear(self):
        import paddle_tpu.quantization as q
        x = paddle.to_tensor(np.array([0.5, -0.25], np.float32))
        s = paddle.to_tensor(np.array(0.01, np.float32))
        qd = q.dequantize_linear(q.quantize_linear(x, s), s)
        np.testing.assert_allclose(qd.numpy(), x.numpy(), atol=0.01)


class TestGeometricSampling:
    def test_sample_neighbors_counts(self):
        # CSC: node 0 has neighbors [1, 2]; node 1 has [0]
        row = paddle.to_tensor(np.array([1, 2, 0]))
        colptr = paddle.to_tensor(np.array([0, 2, 3]))
        nodes = paddle.to_tensor(np.array([0, 1]))
        neigh, cnt = paddle.geometric.sample_neighbors(
            row, colptr, nodes, sample_size=2)
        assert cnt.numpy().tolist() == [2, 1]
        assert neigh.numpy()[1, 1] == -1   # padded


class TestEngineOpsSurface:
    def test_edit_distance_known(self):
        a = paddle.to_tensor(np.array([[1, 2, 3, 4, -1]], np.int64))
        b = paddle.to_tensor(np.array([[1, 3, 4, -1]], np.int64))
        d = paddle.edit_distance(a, b, normalized=False).numpy()
        assert d[0] == 1.0

    def test_top_p_keeps_nucleus(self):
        x = paddle.to_tensor(np.array([[0.6, 0.3, 0.09, 0.01]], np.float32))
        ids = set()
        for seed in range(8):
            _, i = paddle.top_p_sampling(x, paddle.to_tensor(
                np.array([0.5], np.float32)), seed=seed)
            ids.add(int(i.numpy()[0, 0]))
        assert ids == {0}  # 0.6 alone exceeds p=0.5


class TestFusedLayers:
    """The incubate fused Layer zoo forwards + trains."""

    def test_encoder_layer_and_parts(self):
        import paddle_tpu.incubate.nn as inn
        x = paddle.to_tensor(np.random.RandomState(0).randn(
            2, 6, 16).astype(np.float32))
        enc = inn.FusedTransformerEncoderLayer(16, 4, 32)
        assert enc(x).shape == [2, 6, 16]
        rms = inn.FusedRMSNorm(16)
        assert rms(x).shape == [2, 6, 16]
        lin = inn.FusedLinear(16, 8)
        assert lin(x).shape == [2, 6, 8]
        bdr = inn.FusedBiasDropoutResidualLayerNorm(16, dropout_rate=0.0)
        assert bdr(x, x).shape == [2, 6, 16]

    def test_fused_encoder_trains(self):
        import paddle_tpu.incubate.nn as inn
        import paddle_tpu.nn as nn
        model = nn.Sequential(
            inn.FusedTransformerEncoderLayer(8, 2, 16),
            nn.Flatten(), nn.Linear(8 * 4, 2))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(
            4, 4, 8).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 0, 1]))
        lossf = nn.CrossEntropyLoss()
        first = None
        for i in range(6):
            loss = lossf(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss.numpy())
        assert float(loss.numpy()) < first


class TestTransformClasses:
    """The round-3 transform class zoo composes into working pipelines."""

    def test_full_augmentation_pipeline(self):
        import random as pyrandom
        from paddle_tpu.vision import transforms as T
        pyrandom.seed(0)
        np.random.seed(0)
        img = (np.random.rand(16, 20, 3) * 255).astype(np.uint8)
        pipe = T.Compose([
            T.RandomRotation(10),
            T.RandomAffine(5, translate=(0.1, 0.1)),
            T.RandomPerspective(prob=1.0, distortion_scale=0.2),
            T.ContrastTransform(0.2), T.SaturationTransform(0.2),
            T.HueTransform(0.1), T.RandomErasing(prob=1.0),
            T.Grayscale(3),
        ])
        out = pipe(img)
        assert out.shape == (16, 20, 3) and out.dtype == np.uint8
        # grayscale: all three channels equal
        assert np.array_equal(out[..., 0], out[..., 1])

    def test_zero_value_transforms_are_identity(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.RandomState(1).rand(8, 8, 3) * 255).astype(
            np.uint8)
        np.testing.assert_array_equal(T.ContrastTransform(0)(img), img)
        np.testing.assert_array_equal(T.HueTransform(0)(img), img)
        np.testing.assert_array_equal(
            T.RandomErasing(prob=0.0)(img), img)

    def test_seeded_pipeline_is_deterministic(self):
        import random as pyrandom
        from paddle_tpu.vision import transforms as T
        img = (np.random.RandomState(2).rand(12, 12, 3) * 255).astype(
            np.uint8)
        pipe = T.Compose([T.RandomRotation(15), T.RandomErasing(prob=1.0),
                          T.ContrastTransform(0.3)])
        pyrandom.seed(11)
        a = pipe(img)
        pyrandom.seed(11)
        b = pipe(img)
        np.testing.assert_array_equal(a, b)

    def test_random_erasing_tensor_chw(self):
        import random as pyrandom
        pyrandom.seed(0)
        from paddle_tpu.vision import transforms as T
        t = paddle.to_tensor(np.ones((3, 8, 10), np.float32))
        out = T.RandomErasing(prob=1.0)(t)
        assert type(out).__name__ == "Tensor" and out.shape == [3, 8, 10]
        # a SPATIAL patch is erased identically across channels
        z = out.numpy() == 0
        assert z.any() and np.array_equal(z[0], z[1])


class TestOptimizerZoo:
    """Round-3 optimizer/scheduler additions converge on a regression."""

    def _fit(self, opt_cls, steps=60, **kw):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = nn.Linear(4, 1)
        opt = opt_cls(parameters=m.parameters(), **kw)
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.randn(32, 4).astype("float32"))
        Y = paddle.to_tensor(
            X.numpy() @ np.array([[1.], [2.], [-1.], [.5]], np.float32))
        lossf = nn.MSELoss()
        first = None
        for _ in range(steps):
            l = lossf(m(X), Y)
            l.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(l.numpy())
        return first, float(l.numpy())

    @pytest.mark.parametrize("name,kw", [
        ("Rprop", {}),
        ("ASGD", dict(learning_rate=0.05, batch_num=4)),
        ("NAdam", dict(learning_rate=0.1)),
        ("RAdam", dict(learning_rate=0.1)),
    ])
    def test_new_optimizers_converge(self, name, kw):
        first, last = self._fit(getattr(paddle.optimizer, name), **kw)
        assert last < first * 0.5, (name, first, last)

    def test_lbfgs_closure(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = nn.Linear(4, 1)
        opt = paddle.optimizer.LBFGS(learning_rate=0.5,
                                     parameters=m.parameters())
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.randn(32, 4).astype("float32"))
        Y = paddle.to_tensor(
            X.numpy() @ np.array([[1.], [2.], [-1.], [.5]], np.float32))
        lossf = nn.MSELoss()

        def closure():
            opt.clear_grad()
            l = lossf(m(X), Y)
            l.backward()
            return l

        for _ in range(15):
            l = opt.step(closure)
        assert float(l.numpy()) < 1e-3

    def test_new_schedulers(self):
        from paddle_tpu.optimizer.lr import LinearLR, MultiplicativeDecay
        sch = LinearLR(0.1, total_steps=10, start_factor=0.5)
        assert abs(sch() - 0.05) < 1e-9
        for _ in range(10):
            sch.step()
        assert abs(sch() - 0.1) < 1e-9
        md = MultiplicativeDecay(0.1, lambda e: 0.9)
        md.step()
        md.step()
        assert abs(md() - 0.1 * 0.81) < 1e-9

    def test_lookahead_and_model_average(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = nn.Linear(4, 1)
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=m.parameters())
        la = paddle.incubate.LookAhead(inner, alpha=0.5, k=2)
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.randn(16, 4).astype("float32"))
        Y = paddle.to_tensor(rng.randn(16, 1).astype("float32"))
        lossf = nn.MSELoss()
        first = None
        for _ in range(10):
            l = lossf(m(X), Y)
            l.backward()
            la.step()
            la.clear_grad()
            if first is None:
                first = float(l.numpy())
        assert float(l.numpy()) < first
        ma = paddle.incubate.ModelAverage(parameters=m.parameters())
        for _ in range(3):
            ma.step()
        w0 = m.weight.numpy().copy()
        ma.apply()
        ma.restore()
        np.testing.assert_allclose(m.weight.numpy(), w0)


class TestStaticNN:
    def test_program_with_static_nn_layers(self):
        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [None, 1, 8, 8], "float32")
                h = static.nn.conv2d(x, 4, 3, act="relu")
                h = static.nn.batch_norm(h)
                h = static.nn.fc(h, 10, activation="softmax")
        finally:
            paddle.disable_static()
        exe = static.Executor()
        out = exe.run(prog, feed={
            "x": np.random.rand(2, 1, 8, 8).astype("float32")},
            fetch_list=[h])
        assert out[0].shape == (2, 10)
        np.testing.assert_allclose(out[0].sum(1), 1.0, rtol=1e-5)


class TestHubAndSharding:
    def test_hub_local_roundtrip(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "import paddle_tpu.nn as nn\n"
            "def tiny(width=8):\n"
            "    '''tiny model.'''\n"
            "    return nn.Linear(4, width)\n")
        repo = str(tmp_path)
        assert paddle.hub.list(repo) == ["tiny"]
        assert "tiny model" in paddle.hub.help(repo, "tiny")
        m = paddle.hub.load(repo, "tiny", width=16)
        assert m(paddle.to_tensor(
            np.ones((2, 4), np.float32))).shape == [2, 16]
        with pytest.raises(NotImplementedError):
            paddle.hub.load("user/repo", "x", source="github")

    def test_group_sharded_parallel_places_params(self):
        from paddle_tpu.parallel.topology import build_mesh, set_mesh
        from paddle_tpu.distributed.sharding import (
            group_sharded_parallel, save_group_sharded_model)
        import paddle_tpu.nn as nn
        set_mesh(build_mesh(dp=2, sharding=4))
        model = nn.Linear(16, 64)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, level="p_g_os")
        assert "sharding" in str(model.weight._data.sharding)
        import tempfile, os
        with tempfile.TemporaryDirectory() as d:
            save_group_sharded_model(model, d, opt)
            assert os.path.exists(os.path.join(d, "model.pdparams"))


class TestRound4OpTail:
    """The COVERAGE.md 'known todo' tail, closed in round 4."""

    def test_lu_solve_roundtrip(self):
        import numpy as np
        import paddle_tpu as paddle
        a = np.random.RandomState(0).randn(4, 4).astype("float32") \
            + 4 * np.eye(4, dtype="float32")
        b = np.random.RandomState(1).randn(4, 2).astype("float32")
        lu_t, piv = paddle.linalg.lu(paddle.to_tensor(a))
        x = paddle.linalg.lu_solve(paddle.to_tensor(b), lu_t, piv)
        np.testing.assert_allclose(x.numpy(), np.linalg.solve(a, b),
                                   rtol=1e-4, atol=1e-5)

    def test_histc_matches_histogram(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.arange(12, dtype="float32"))
        np.testing.assert_array_equal(
            paddle.histc(x, bins=4).numpy(),
            paddle.histogram(x, bins=4).numpy())

    def test_weighted_sample_neighbors_prefers_heavy_edges(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import geometric
        # node 0 has neighbors 1 (weight ~0) and 2 (weight huge)
        row = paddle.to_tensor(np.array([1, 2], np.int64))
        colptr = paddle.to_tensor(np.array([0, 2, 2, 2], np.int64))
        w = paddle.to_tensor(np.array([1e-9, 1e9], np.float32))
        nodes = paddle.to_tensor(np.array([0], np.int64))
        neigh, cnt = geometric.weighted_sample_neighbors(
            row, colptr, w, nodes, sample_size=2)
        assert int(cnt.numpy()[0]) == 2
        # WITHOUT replacement (r5, ADVICE r4 item 1 — Gumbel top-k):
        # both neighbors are returned exactly once, the heavy edge first
        got = neigh.numpy()[0]
        assert sorted(got.tolist()) == [1, 2]
        assert got[0] == 2  # heavy edge wins the top slot

    def test_fused_gemm_epilogue_activations(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import incubate
        x = paddle.to_tensor(-np.ones((2, 3), "float32"))
        y = paddle.to_tensor(np.ones((3, 4), "float32"))
        b = paddle.to_tensor(np.zeros((4,), "float32"))
        out = incubate.nn.functional.fused_gemm_epilogue(
            x, y, b, activation="relu")
        assert float(out.numpy().max()) == 0.0
        out = incubate.nn.functional.fused_gemm_epilogue(
            x, y, b, activation="none")
        np.testing.assert_allclose(out.numpy(), -3 * np.ones((2, 4)),
                                   rtol=1e-6)

    def test_block_multihead_attention_respects_lengths(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import incubate
        B, S, H, D = 2, 4, 2, 8
        rng = np.random.RandomState(3)
        qkv = paddle.to_tensor(rng.randn(B, S, 3 * H * D).astype("float32"))
        ck = paddle.to_tensor(np.zeros((B, 8, H, D), "float32"))
        cv = paddle.to_tensor(np.zeros((B, 8, H, D), "float32"))
        lens = paddle.to_tensor(np.array([4, 4], np.int64))
        out, ck2, cv2 = incubate.nn.functional.block_multihead_attention(
            qkv, ck, cv, lens, num_heads=H, head_dim=D)
        # caches prefilled with k/v
        k = qkv.numpy()[..., H * D:2 * H * D].reshape(B, S, H, D)
        np.testing.assert_allclose(ck2.numpy()[:, :S], k, rtol=1e-6)
        # first position attends only to itself -> equals its value row
        v = qkv.numpy()[..., 2 * H * D:].reshape(B, S, H, D)
        np.testing.assert_allclose(out.numpy()[:, 0],
                                   v[:, 0].reshape(B, H * D), atol=1e-5)

    def test_sparse_batchnorm_dim_aliases(self):
        from paddle_tpu import sparse
        assert sparse.nn.BatchNorm3D is sparse.nn.BatchNorm
        assert sparse.nn.BatchNorm1D is sparse.nn.BatchNorm
