"""Vision model-zoo smoke tests (SURVEY.md §2.4 paddle.vision row): tiny
inputs, output shapes, param sanity, one grad step per family."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models

RNG = np.random.default_rng(3)

# The zoo dominates suite wall time (~10 min of the 28-min full run);
# excluded from the default gate, run with `pytest -m slow` / `-m ''`.
pytestmark = pytest.mark.slow


def img(n=1, size=64):
    return paddle.to_tensor(RNG.standard_normal((n, 3, size, size))
                            .astype(np.float32))


class TestZooForward:
    @pytest.mark.parametrize("ctor,kw,size", [
        (models.mobilenet_v3_small, dict(num_classes=10), 64),
        (models.mobilenet_v3_large, dict(num_classes=10), 64),
        (models.densenet121, dict(num_classes=10), 64),
        (models.shufflenet_v2_x0_25, dict(num_classes=10), 64),
        (models.shufflenet_v2_swish, dict(num_classes=10), 64),
        (models.squeezenet1_0, dict(num_classes=10), 96),
        (models.squeezenet1_1, dict(num_classes=10), 96),
        (models.inception_v3, dict(num_classes=10), 128),
    ])
    def test_forward_shape(self, ctor, kw, size):
        m = ctor(**kw)
        m.eval()
        out = m(img(2, size))
        assert out.shape == [2, 10]
        assert np.isfinite(out.numpy()).all()

    def test_googlenet_three_heads(self):
        m = models.googlenet(num_classes=7)
        m.eval()
        out, aux1, aux2 = m(img(1, 96))
        assert out.shape == [1, 7] and aux1.shape == [1, 7] \
            and aux2.shape == [1, 7]

    def test_pretrained_raises(self):
        with pytest.raises(NotImplementedError):
            models.densenet121(pretrained=True)

    def test_one_train_step(self):
        m = models.shufflenet_v2_x0_25(num_classes=4)
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=m.parameters())
        loss = paddle.nn.CrossEntropyLoss()(
            m(img(2, 64)), paddle.to_tensor(np.array([1, 3])))
        loss.backward()
        opt.step()
        assert np.isfinite(loss.numpy())

    def test_scaled_variants(self):
        m = models.mobilenet_v3_small(scale=0.5, num_classes=5)
        m.eval()
        assert m(img(1, 64)).shape == [1, 5]
        m2 = models.DenseNet(layers=169, num_classes=5)
        m2.eval()
        assert m2(img(1, 64)).shape == [1, 5]
