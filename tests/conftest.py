"""Test config: force CPU platform with 8 virtual devices.

Carry-over from the reference's test strategy (SURVEY.md §4): multi-node is
simulated locally — their trick is multi-process on 127.0.0.1; ours is
XLA host-platform fake devices for in-process SPMD tests. The axon TPU plugin
(sitecustomize) is overridden by updating jax config before any backend init.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reseed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    yield
