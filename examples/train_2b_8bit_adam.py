"""Single-chip flagship-class training: bf16 params + 8-bit Adam moments.

This is the bench.py headline configuration (round 2): a 2.0B-param Llama
whose ENTIRE train state fits one 16GB v5e chip because the Adam moments
are stored as blockwise float8 codes (~2 bytes/param instead of 8 —
optimizer/quant_state.py). Run small anywhere:

  JAX_PLATFORMS=cpu python examples/train_2b_8bit_adam.py

On a real chip, scale the config toward bench.py's 2B shape.
"""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, train


def main(steps=5):
    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=9472,
            num_hidden_layers=11, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=2048,
            param_dtype=jnp.bfloat16)
        batch, seq = 4, 2048
    else:
        cfg = llama.LlamaConfig.tiny(num_hidden_layers=2, use_flash=False)
        batch, seq = 8, 64

    # the 8-bit path streams clip-by-global-norm through its chunked
    # update (no second grad tree), so the recipe's clip stays on at 2B
    tx = train.make_optimizer(1e-4, state_quant="8bit", grad_clip=1.0)
    state = train.init_state(jax.random.key(0), cfg, tx, mesh=None)
    step = train.make_train_step(cfg, tx, mesh=None)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)),
        jnp.int32)
    for i in range(steps):
        state, metrics = step(state, tokens)
        print(f"step {i}: loss {float(metrics['loss']):.4f}  "
              f"params {llama.num_params(cfg)/1e9:.2f}B")


if __name__ == "__main__":
    main()
