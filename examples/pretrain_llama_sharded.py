"""Pretrain a small Llama over the full hybrid mesh (dp/sharding/sep/mp) —
the flagship GSPMD path (SURVEY.md §7 M4-M5).

Run single-host (virtual devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/pretrain_llama_sharded.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, train
from paddle_tpu.parallel import topology


def main(steps=5):
    n = len(jax.devices())
    mp = 2 if n % 2 == 0 else 1
    sharding = 2 if n % 4 == 0 else 1
    mesh = topology.build_mesh(dp=n // (mp * sharding), sharding=sharding,
                               mp=mp)
    cfg = llama.LlamaConfig.tiny(num_hidden_layers=4)
    tx = train.make_optimizer(3e-4)
    state = train.init_state(jax.random.key(0), cfg, tx, mesh=mesh)
    step = train.make_train_step(cfg, tx, mesh=mesh)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 128)), jnp.int32)
    for i in range(steps):
        state, metrics = step(state, tokens)
        print(f"step {i}: loss {float(metrics['loss']):.4f}")


if __name__ == "__main__":
    main()
