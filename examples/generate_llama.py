"""KV-cache generation with the flagship Llama family (round 2).

The decode loop is ONE compiled lax.scan (nlp/generation.py) — no host
round-trip per token, unlike the reference's PaddleNLP predict loop.

Run anywhere:
  JAX_PLATFORMS=cpu python examples/generate_llama.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, generation


def main():
    cfg = llama.LlamaConfig.tiny(num_hidden_layers=2, use_flash=False)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8)),
        jnp.int32)

    greedy = jax.jit(lambda p, t: generation.generate(
        p, t, cfg, max_new_tokens=16))(params, prompt)
    print("greedy      :", np.asarray(greedy).tolist())

    sampled = generation.generate(
        params, prompt, cfg, max_new_tokens=16, greedy=False,
        temperature=0.8, top_k=40, top_p=0.95, key=jax.random.PRNGKey(7))
    print("top-k/top-p :", np.asarray(sampled).tolist())


if __name__ == "__main__":
    main()
