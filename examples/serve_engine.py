"""Async request serving through paddle_tpu.serving.ServingEngine (PR 1).

Where examples/serve_llm.py serves one fixed batch per Predictor.run(),
the ServingEngine serves a STREAM of requests: a background thread keeps
the paged-KV continuous batcher saturated from a priority queue, tokens
flow back through per-request channels (blocking or streaming), requests
carry deadlines / stop tokens / cancellation, and the engine exports a
metrics snapshot (TTFT, queue wait, KV-block utilization).

Run anywhere:
  JAX_PLATFORMS=cpu python examples/serve_engine.py
"""
import numpy as np
import jax

from paddle_tpu import serving
from paddle_tpu.nlp import llama


def main():
    cfg = llama.LlamaConfig.tiny(num_hidden_layers=2, use_flash=False)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompt = lambda n: rng.integers(1, cfg.vocab_size, n).tolist()

    eng = serving.ServingEngine(params, cfg, max_batch=2, block_size=8,
                                max_total_len=64, max_new_tokens=16,
                                chunk=4)

    # blocking one-shot
    out = eng.generate(prompt(6))
    print("generate:", out)

    # streaming consumption
    print("stream:  ", end="", flush=True)
    for tok in eng.stream(prompt(9), max_new_tokens=8):
        print(tok, end=" ", flush=True)
    print()

    # async handles: mixed priorities + a cancellation
    hi = eng.submit(prompt(5), priority=0)
    lo = eng.submit(prompt(5), priority=5)
    doomed = eng.submit(prompt(5), priority=9)
    doomed.cancel()
    print("hi-prio: ", hi.result())
    print("lo-prio: ", lo.result())
    doomed.wait()
    print("doomed:  ", doomed.state.name)

    snap = eng.snapshot()
    print("counters:", snap["counters"])
    print("ttft_s:  ", {k: round(v, 4) for k, v in
                        snap["histograms"]["ttft_s"].items()})
    print("pool:    ", snap["allocator"])
    eng.shutdown()     # graceful drain


if __name__ == "__main__":
    main()
