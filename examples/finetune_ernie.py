"""Finetune the ERNIE encoder on a synthetic classification task
(BASELINE config-1 shape).

Run: python examples/finetune_ernie.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import ernie


def main(steps=20):
    cfg = ernie.ErnieConfig.tiny(num_labels=2)
    params = ernie.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (16, 32)))
    labels = jnp.asarray(rng.integers(0, 2, (16,)))

    step = jax.jit(jax.value_and_grad(
        lambda p: ernie.finetune_loss(p, ids, labels, cfg)))
    for i in range(steps):
        loss, grads = step(params)
        params = jax.tree.map(lambda p, g: p - 5e-2 * g, params, grads)
        if i % 5 == 0:
            print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
