"""TP/DP-sharded LLM serving through inference.create_predictor (round 3).

The serving analog of the reference's PaddleNLP `llm/` predict with
--tensor_parallel_degree: save a generation-ready checkpoint (.pdllm),
point an inference.Config at it, pick mp/dp degrees, and the Predictor
runs the whole prefill + decode scan as ONE compiled TP/DP-sharded
program — KV cache resident and mp-sharded across the loop
(nlp/generation.cache_spec), weights placed per llama.infer_param_specs.

Run anywhere (sized to the host):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/serve_llm.py
On a real v5e chip this serves the bench.py 2B-class config single-chip;
with 8 devices it runs mp=2 x dp=2.
"""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import inference
from paddle_tpu.inference import llm as illm
from paddle_tpu.nlp import llama


def main():
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        # the 2B-class single-chip config from examples/train_2b_8bit_adam
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=9472,
            num_hidden_layers=11, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=2048,
            param_dtype=jnp.bfloat16)
        batch, plen, new = 4, 128, 64
    else:
        cfg = llama.LlamaConfig.tiny(num_hidden_layers=2, use_flash=False)
        batch, plen, new = 2, 8, 16

    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prefix = "/tmp/paddle_tpu_llm_demo"
    illm.save_llm(prefix, params, cfg)
    print(f"saved {llama.num_params(cfg)/1e9:.2f}B-param checkpoint "
          f"-> {prefix}{illm.LLM_SUFFIX}")

    config = inference.Config(prefix)
    config.enable_llm_generation(max_new_tokens=new, decode_strategy="sampling",
                                 temperature=0.8, top_k=40, top_p=0.95)
    ndev = len(jax.devices())
    if ndev >= 4:
        config.set_llm_parallel(mp=2, dp=2)
        print("serving with mp=2 dp=2")
    elif ndev >= 2:
        config.set_llm_parallel(mp=2)
        print("serving with mp=2")
    predictor = inference.create_predictor(config)

    prompt = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, plen)).astype(np.int32)
    predictor.get_input_handle("input_ids").copy_from_cpu(prompt)
    import time
    predictor.run()  # warm-up trace+compile
    t0 = time.perf_counter()
    (out,) = predictor.run()
    dt = time.perf_counter() - t0
    toks = out.shape[0] * out.shape[1]
    print(f"generated {out.shape} in {dt*1e3:.1f} ms "
          f"({toks/dt:.0f} tok/s)")
    print("first row:", out[0, :12].tolist())


if __name__ == "__main__":
    main()
