"""Train ResNet-18 on synthetic CIFAR-shaped data (BASELINE config-0 shape).

Run: python examples/train_resnet_cifar.py [--steps 50]
"""
import argparse

import numpy as np

import paddle_tpu as paddle


def main(steps=50, batch=32):
    model = paddle.vision.models.resnet18(num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    parameters=model.parameters())
    loss_fn = paddle.nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    for step in range(steps):
        x = paddle.to_tensor(
            rng.standard_normal((batch, 3, 32, 32)).astype("float32"))
        y = paddle.to_tensor(rng.integers(0, 10, batch))
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 10 == 0:
            print(f"step {step}: loss {float(loss.numpy()):.4f}")
    return model


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    main(p.parse_args().steps)
