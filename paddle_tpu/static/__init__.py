"""paddle.static — Program/Executor graph mode over traced replay + jax.jit.

Reference parity: python/paddle/static/ (Program/Executor API, data(),
save/load_inference_model) over ProgramDesc/PIR + InterpreterCore —
upstream-canonical, unverified, SURVEY.md §0, §2.4, §3.4-3.5.

TPU-native design: there is no IR to rebuild — XLA's HLO is the IR. A
Program is a replayable op-record list captured from the SAME eager op layer
(ops/_registry.eager routes here in static mode), and Executor.run compiles
the pruned record graph with jax.jit per feed-shape signature. Parameters are
leaves read at run time (so set_state_dict/opt updates are visible), which is
exactly the reference's scope-variable semantics; initializer records that
produced a Parameter are pruned like a startup program that already ran.
save/load_inference_model serialize the jitted callable with jax.export.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import dtype as dtypes
from ..ops import _registry

from .. import static_nn as nn  # noqa: F401  (paddle.static.nn)

__all__ = [
    "nn", "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "InputSpec",
    "save_inference_model", "load_inference_model", "global_scope",
    "name_scope", "enable_static", "disable_static", "in_static_mode",
]


class _Record:
    __slots__ = ("raw", "arg_slots", "kw_slots", "out_ids", "name")

    def __init__(self, raw, arg_slots, kw_slots, out_ids, name):
        self.raw = raw
        self.arg_slots = arg_slots    # list of ("var", id) | ("lit", value)
        self.kw_slots = kw_slots      # dict key → slot
        self.out_ids = out_ids        # list of tensor ids
        self.name = name


class Program:
    """An op-record list + feed-variable table (ProgramDesc analog)."""

    def __init__(self):
        self.records: List[_Record] = []
        self.feed_vars: Dict[str, Tensor] = {}
        self._vars: Dict[int, Tensor] = {}  # keep captured tensors alive
        self._cache: Dict = {}

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.records = list(self.records)
        p.feed_vars = dict(self.feed_vars)
        p._vars = dict(self._vars)
        return p

    def global_block(self):
        return self

    @property
    def ops(self):
        return self.records

    def var(self, name: str):
        for t in self._vars.values():
            if getattr(t, "name", None) == name:
                return t
        raise KeyError(name)

    def list_vars(self):
        return list(self._vars.values())

    def __repr__(self):
        lines = [f"Program({len(self.records)} ops)"]
        for r in self.records:
            lines.append(f"  {r.name}")
        return "\n".join(lines)

    # -- capture ------------------------------------------------------------
    def _track(self, t: Tensor):
        self._vars[id(t)] = t

    def _record(self, raw, args, kwargs, outs, name):
        def slot(v):
            if isinstance(v, Tensor):
                self._track(v)
                return ("var", id(v))
            return ("lit", v)

        rec = _Record(raw, [slot(a) for a in args],
                      {k: slot(v) for k, v in kwargs.items()},
                      [id(o) for o in outs], name)
        for o in outs:
            self._track(o)
        self.records.append(rec)

    # -- replay -------------------------------------------------------------
    def _live_records(self, fetch_ids, feed_ids):
        """Backward slice from fetches; Parameters and eager tensors are
        leaves (their records, e.g. initializers, are pruned — the reference
        runs those once in the startup program)."""
        produced_by = {}
        for rec in self.records:
            for oid in rec.out_ids:
                produced_by[oid] = rec
        needed, live, stack = set(), [], list(fetch_ids)
        seen = set()
        while stack:
            vid = stack.pop()
            if vid in seen or vid in feed_ids:
                continue
            seen.add(vid)
            var = self._vars.get(vid)
            if isinstance(var, Parameter):
                continue  # leaf: read current value at run time
            rec = produced_by.get(vid)
            if rec is None or id(rec) in needed:
                continue
            needed.add(id(rec))
            for s in list(rec.arg_slots) + list(rec.kw_slots.values()):
                if s[0] == "var":
                    stack.append(s[1])
        return [r for r in self.records if id(r) in needed]

    def _build_fn(self, feed_names, fetch_ids):
        feed_ids = {id(self.feed_vars[n]): n for n in feed_names}
        live = self._live_records(fetch_ids, set(feed_ids))
        leaf_ids = set()
        produced = set()
        for rec in live:
            produced.update(rec.out_ids)
        for rec in live:
            for s in list(rec.arg_slots) + list(rec.kw_slots.values()):
                if s[0] == "var" and s[1] not in produced and \
                        s[1] not in feed_ids:
                    leaf_ids.add(s[1])
        for fid in fetch_ids:
            if fid not in produced and fid not in feed_ids:
                leaf_ids.add(fid)
        leaf_ids = sorted(leaf_ids)

        def fn(feed_arrays, leaf_arrays):
            env = {}
            for n, a in feed_arrays.items():
                env[id(self.feed_vars[n])] = a
            env.update(zip(leaf_ids, leaf_arrays))

            def resolve(s):
                return env[s[1]] if s[0] == "var" else s[1]

            for rec in live:
                out = rec.raw(*[resolve(s) for s in rec.arg_slots],
                              **{k: resolve(s)
                                 for k, s in rec.kw_slots.items()})
                outs = tuple(out) if isinstance(out, (tuple, list)) else (out,)
                env.update(zip(rec.out_ids, outs))
            return [env[fid] for fid in fetch_ids]

        return jax.jit(fn), leaf_ids

    def run(self, feed: Dict[str, np.ndarray], fetch_list: Sequence):
        fetch_ids = tuple(id(f if isinstance(f, Tensor) else self.var(f))
                          for f in (fetch_list or []))
        feed = feed or {}
        key = (tuple(sorted(feed)), fetch_ids)
        if key not in self._cache:
            self._cache[key] = self._build_fn(sorted(feed), fetch_ids)
        fn, leaf_ids = self._cache[key]
        feed_arrays = {}
        for n, v in feed.items():
            var = self.feed_vars.get(n)
            want = None if var is None else np.dtype(var.dtype)
            a = jnp.asarray(v, dtype=want)
            feed_arrays[n] = a
        leaf_arrays = [self._vars[i]._data for i in leaf_ids]
        outs = fn(feed_arrays, leaf_arrays)
        return [np.asarray(o) for o in outs]


_main_program = Program()
_startup_program = Program()
_static_mode = False


def default_main_program() -> Program:
    """The Program op records are currently captured into (the
    reference's global main ProgramDesc); swap it with program_guard."""
    return _main_program


def default_startup_program() -> Program:
    """The Program initializer records capture into. Here it is mostly
    vestigial: Parameters are leaves whose initializers are pruned at
    replay, which IS the 'startup program already ran' semantics."""
    return _startup_program


class program_guard:
    """Context manager swapping the default main (and optionally
    startup) Program, so ops captured inside the block record into the
    given graphs (reference paddle.static.program_guard)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        global _main_program, _startup_program
        self._saved = (_main_program, _startup_program)
        _main_program = self.main
        if self.startup is not None:
            _startup_program = self.startup
        return self.main

    def __exit__(self, *exc):
        global _main_program, _startup_program
        _main_program, _startup_program = self._saved
        return False


def in_static_mode() -> bool:
    """True between enable_static() and disable_static() — i.e. while
    the eager op layer records into a Program instead of executing."""
    return _static_mode


def _capture(raw, args, kwargs, name):
    """ops/_registry capture hook: run on placeholder values for shape/dtype
    propagation (InferMeta analog), record into the current program."""
    arrs = [a._data if isinstance(a, Tensor) else a for a in args]
    kw = {k: (v._data if isinstance(v, Tensor) else v)
          for k, v in kwargs.items()}
    out = raw(*arrs, **kw)
    multi = isinstance(out, (tuple, list))
    outs = tuple(out) if multi else (out,)
    wrapped = tuple(Tensor(o, stop_gradient=True) for o in outs)
    _main_program._record(raw, args, kwargs, wrapped, name)
    return wrapped if multi else wrapped[0]


def enable_static():
    """Enter graph mode: eager ops stop executing and start recording
    into default_main_program() (shape/dtype propagate via placeholder
    evaluation, the InferMeta analog)."""
    global _static_mode
    _static_mode = True
    _registry._capture_hook = _capture


def disable_static():
    """Leave graph mode: the op layer executes eagerly again; captured
    Programs stay replayable through Executor.run."""
    global _static_mode
    _static_mode = False
    _registry._capture_hook = None


def data(name: str, shape: Sequence[Optional[int]], dtype="float32",
         lod_level: int = 0) -> Tensor:
    """Feed placeholder. Dynamic dims (None/-1) capture as size 1; Executor
    re-jits per concrete feed shape, so replay stays shape-polymorphic."""
    concrete = [1 if (d is None or d < 0) else int(d) for d in shape]
    dt = dtypes.convert_dtype(dtype)
    t = Tensor(jnp.zeros(concrete, dtype=dt), name=name)
    t.is_data = True
    t.declared_shape = list(shape)
    _main_program.feed_vars[name] = t
    _main_program._track(t)
    return t


class InputSpec:
    """paddle.static.InputSpec parity (used by jit.save / to_static)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


class Executor:
    """paddle.static.Executor parity; the 'place' is decorative (XLA owns
    placement; SURVEY.md §2.6 item 4)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy: bool = True):
        program = program if program is not None else _main_program
        if isinstance(program, CompiledInferenceProgram):
            return program.run(feed, fetch_list)
        if not fetch_list:
            return []  # startup programs: initializers already ran eagerly
        return program.run(feed or {}, fetch_list)

    def close(self):
        pass


class _Scope:
    def var(self, name):
        return None

    def find_var(self, name):
        return None


_scope = _Scope()


def global_scope():
    """The reference's global variable Scope. Here a stub: variables
    live on Tensors (leaves read at run time), so the scope has nothing
    to resolve — kept for API-compatible callers that probe it."""
    return _scope


class name_scope:
    """No-op naming context (reference: prefixes op names in the
    ProgramDesc). HLO keeps its own metadata, so this only preserves
    the with-block API shape."""

    def __init__(self, prefix=""):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# ---------------------------------------------------------------------------
# Inference save/load: jax.export of the pruned, jitted program.
# ---------------------------------------------------------------------------

class CompiledInferenceProgram:
    """What load_inference_model returns in place of a Program."""

    def __init__(self, exported, feed_names, fetch_names):
        self._exported = exported
        self.feed_names = feed_names
        self.fetch_names = fetch_names

    def run(self, feed, fetch_list=None):
        args = [jnp.asarray(feed[n]) for n in self.feed_names]
        outs = self._exported.call(*args)
        return [np.asarray(o) for o in outs]


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program: Optional[Program] = None):
    """Serialize feed→fetch as a jax.export artifact (.pdmodel analog;
    reference: save_inference_model → ProgramDesc + params, SURVEY.md §3.5 —
    here params are baked into the exported HLO as constants)."""
    from jax import export as jax_export
    program = program if program is not None else _main_program
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    feed_names = [v.name for v in feed_vars]
    fetch_ids = tuple(id(v) for v in fetch_vars)
    key = (tuple(sorted(feed_names)), fetch_ids)
    if key not in program._cache:
        program._cache[key] = program._build_fn(sorted(feed_names), fetch_ids)
    fn, leaf_ids = program._cache[key]
    leaf_arrays = [program._vars[i]._data for i in leaf_ids]

    def infer_fn(*feed_arrays):
        by_name = dict(zip(sorted(feed_names), feed_arrays))
        return fn(by_name, leaf_arrays)

    # dims declared dynamic (None/-1) export as symbolic dims so the loaded
    # model accepts any batch size, like the reference's -1 feed dims
    scope = jax_export.SymbolicScope()
    specs = []
    for i, n in enumerate(sorted(feed_names)):
        var = program.feed_vars[n]
        declared = getattr(var, "declared_shape",
                           list(var._data.shape))
        dims = ",".join(
            f"_dyn{i}_{j}" if (d is None or int(d) < 0) else str(int(d))
            for j, d in enumerate(declared))
        if "_dyn" in dims:
            shape = jax_export.symbolic_shape(dims, scope=scope)
        else:
            shape = tuple(var._data.shape)
        specs.append(jax.ShapeDtypeStruct(shape, var._data.dtype))
    exported = jax_export.export(jax.jit(infer_fn))(*specs)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump({"feed_names": sorted(feed_names),
                     "fetch_names": [getattr(v, "name", str(i))
                                     for i, v in enumerate(fetch_vars)]}, f)


def load_inference_model(path_prefix: str, executor):
    """Load a save_inference_model artifact: deserializes the
    jax.export blob (.pdmodel) + feed/fetch metadata (.pdmeta) and
    returns (program, feed_names, fetch_names) like the reference."""
    from jax import export as jax_export
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    prog = CompiledInferenceProgram(exported, meta["feed_names"],
                                    meta["fetch_names"])
    return prog, meta["feed_names"], meta["fetch_names"]
