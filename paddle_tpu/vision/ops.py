"""paddle.vision.ops — detection ops: nms, box utilities, roi_align/pool,
PSRoIPool-free subset (upstream-canonical python/paddle/vision/ops.py,
unverified — SURVEY.md §0).

TPU-native: nms runs as a fixed-iteration lax.while-free masked loop
(static shapes, no data-dependent python control flow); roi_align is
bilinear gather (same machinery as grid_sample).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._registry import eager, as_array

__all__ = ["box_area", "box_iou", "nms", "roi_align", "roi_pool",
           "distribute_fpn_proposals", "generate_proposals", "DeformConv2D",
           "deform_conv2d"]


def _box_area_raw(boxes):
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def box_area(boxes, name=None):
    return eager(_box_area_raw, (boxes,), {}, name="box_area")


def _box_iou_raw(a, b):
    area_a = _box_area_raw(a)[:, None]
    area_b = _box_area_raw(b)[None, :]
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area_a + area_b - inter + 1e-10)


def box_iou(boxes1, boxes2, name=None):
    return eager(_box_iou_raw, (boxes1, boxes2), {}, name="box_iou")


def _nms_raw(boxes, iou_threshold, scores):
    n = boxes.shape[0]
    order = jnp.argsort(-scores) if scores is not None else jnp.arange(n)
    sb = boxes[order]
    iou = _box_iou_raw(sb, sb)

    def body(i, keep):
        # drop i's lower-ranked overlaps iff i itself is still kept
        sup = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    return order, keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy NMS → kept indices (score-descending). Batched/categorical
    form offsets boxes per category so classes never suppress each other
    (the reference's batched_nms trick)."""
    b = as_array(boxes)
    s = None if scores is None else as_array(scores)
    if category_idxs is not None:
        cat = as_array(category_idxs).astype(b.dtype)
        offset = (jnp.max(b) + 1.0) * cat
        b = b + offset[:, None]
    order, keep = _nms_raw(b, float(iou_threshold),
                           None if s is None else s)
    # kept original-box indices in score-descending order
    idx = np.asarray(order)[np.asarray(keep)]
    out = jnp.asarray(idx, jnp.int64)
    if top_k is not None:
        out = out[:top_k]
    return Tensor(out)


def _roi_align_raw(x, boxes, box_nums, output_size, spatial_scale,
                   sampling_ratio, aligned):
    n, c, h, w = x.shape
    oh, ow = output_size
    num_rois = boxes.shape[0]
    # batch index per roi from box_nums (rois are grouped by image)
    batch_idx = jnp.repeat(jnp.arange(len(box_nums)),
                           jnp.asarray(box_nums),
                           total_repeat_length=num_rois)
    offset = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - offset
    y1 = boxes[:, 1] * spatial_scale - offset
    x2 = boxes[:, 2] * spatial_scale - offset
    y2 = boxes[:, 3] * spatial_scale - offset
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    # the reference adapts samples-per-bin per ROI (ceil(roi/out)); XLA needs
    # static shapes, so we use a fixed grid — 4x4 per bin covers typical
    # detection ROIs well (deviation documented)
    sr = sampling_ratio if sampling_ratio > 0 else 4
    # sample grid: [R, oh*sr, ow*sr]
    ys = (y1[:, None] + rh[:, None] * (jnp.arange(oh * sr) + 0.5)
          / (oh * sr))
    xs = (x1[:, None] + rw[:, None] * (jnp.arange(ow * sr) + 0.5)
          / (ow * sr))

    def bilinear(img, yy, xx):
        # img: [C, H, W]; yy: [P], xx: [Q] → [C, P, Q]
        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        wy = jnp.clip(yy - y0, 0, 1)
        wx = jnp.clip(xx - x0, 0, 1)
        v00 = img[:, y0i][:, :, x0i]
        v01 = img[:, y0i][:, :, x1i]
        v10 = img[:, y1i][:, :, x0i]
        v11 = img[:, y1i][:, :, x1i]
        top = v00 * (1 - wx)[None, None, :] + v01 * wx[None, None, :]
        bot = v10 * (1 - wx)[None, None, :] + v11 * wx[None, None, :]
        return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

    def per_roi(bi, yy, xx):
        img = x[bi]
        samples = bilinear(img, yy, xx)  # [C, oh*sr, ow*sr]
        return samples.reshape(c, oh, sr, ow, sr).mean(axis=(2, 4))

    return jax.vmap(per_roi)(batch_idx, ys, xs)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    nums = [int(v) for v in np.asarray(
        boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)]
    return eager(lambda xa, ba: _roi_align_raw(
        xa, ba, nums, output_size, spatial_scale, sampling_ratio, aligned),
        (x, boxes), {}, name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI (coarse reference semantics via dense sampling + max)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    nums = [int(v) for v in np.asarray(
        boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)]

    def raw(xa, ba):
        n, c, h, w = xa.shape
        oh, ow = output_size
        num_rois = ba.shape[0]
        batch_idx = jnp.repeat(jnp.arange(len(nums)), jnp.asarray(nums),
                               total_repeat_length=num_rois)
        x1 = jnp.floor(ba[:, 0] * spatial_scale)
        y1 = jnp.floor(ba[:, 1] * spatial_scale)
        x2 = jnp.ceil(ba[:, 2] * spatial_scale)
        y2 = jnp.ceil(ba[:, 3] * spatial_scale)
        sr = 4

        def per_roi(bi, ax1, ay1, ax2, ay2):
            rw = jnp.maximum(ax2 - ax1, 1.0)
            rh = jnp.maximum(ay2 - ay1, 1.0)
            ys = jnp.clip(ay1 + rh * (jnp.arange(oh * sr) + 0.5) / (oh * sr),
                          0, h - 1).astype(jnp.int32)
            xs = jnp.clip(ax1 + rw * (jnp.arange(ow * sr) + 0.5) / (ow * sr),
                          0, w - 1).astype(jnp.int32)
            img = xa[bi]
            samples = img[:, ys][:, :, xs]
            return samples.reshape(c, oh, sr, ow, sr).max(axis=(2, 4))

        return jax.vmap(per_roi)(batch_idx, x1, y1, x2, y2)

    return eager(raw, (x, boxes), {}, name="roi_pool")


def distribute_fpn_proposals(*args, **kwargs):
    raise NotImplementedError(
        "distribute_fpn_proposals: detection-pipeline op deferred "
        "(paddle_tpu/vision/ops.py)")


def generate_proposals(*args, **kwargs):
    raise NotImplementedError(
        "generate_proposals: RPN op deferred (paddle_tpu/vision/ops.py)")


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError(
        "deform_conv2d: deferred (paddle_tpu/vision/ops.py) — needs a "
        "Pallas gather-conv kernel")


class DeformConv2D:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "DeformConv2D: deferred (paddle_tpu/vision/ops.py)")


# detection op family (reference home: paddle.vision.ops re-exports the
# detection PHI ops) — implemented in ops/detection.py
from ..ops.detection import (  # noqa: F401,E402
    anchor_generator, bipartite_match, box_clip, box_coder,
    density_prior_box, iou_similarity, matrix_nms, multiclass_nms,
    prior_box, psroi_pool, yolo_box)


def read_file(filename, name=None):
    """paddle.vision.ops.read_file: raw bytes as a uint8 tensor."""
    from ..core.tensor import Tensor
    import numpy as _np
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(_np.frombuffer(data, dtype=_np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """paddle.vision.ops.decode_jpeg via PIL (HWC uint8 -> CHW tensor)."""
    from ..core.tensor import Tensor
    import io as _io
    import numpy as _np
    try:
        from PIL import Image
    except ImportError as e:
        raise NotImplementedError(
            "decode_jpeg needs PIL (paddle_tpu/vision/ops.py)") from e
    raw = bytes(bytearray(_np.asarray(x._data if hasattr(x, "_data")
                                      else x, dtype=_np.uint8)))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    arr = _np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
