"""ShuffleNetV2 — python/paddle/vision/models/shufflenetv2.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from ... import nn
from ... import ops


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), _act(act),
                nn.Conv2D(branch_c, branch_c, 3, stride=1, padding=1,
                          groups=branch_c, bias_attr=False),
                nn.BatchNorm2D(branch_c),
                nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), _act(act))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), _act(act))
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), _act(act),
                nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                          groups=branch_c, bias_attr=False),
                nn.BatchNorm2D(branch_c),
                nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), _act(act))
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        if self.stride == 1:
            a, b = ops.split(x, 2, axis=1)
            out = ops.concat([a, self.branch2(b)], axis=1)
        else:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return self.shuffle(out)


_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        out_c = _STAGE_OUT[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, out_c[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(out_c[0]), _act(act))
        self.max_pool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = out_c[0]
        for i, reps in enumerate(stage_repeats):
            c = out_c[i + 1]
            stages.append(_InvertedResidual(in_c, c, 2, act))
            for _ in range(reps - 1):
                stages.append(_InvertedResidual(c, c, 1, act))
            in_c = c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, out_c[-1], 1, bias_attr=False),
            nn.BatchNorm2D(out_c[-1]), _act(act))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(out_c[-1], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights unavailable offline "
            "(paddle_tpu/vision/models/shufflenetv2.py)")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, act="swish", pretrained=pretrained, **kwargs)
