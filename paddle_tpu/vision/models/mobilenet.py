"""MobileNet v1/v2 — python/paddle/vision/models/mobilenetv{1,2}.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from ... import nn


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1, relu6=True):
        pad = (kernel - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6() if relu6 else nn.ReLU())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        def dw_sep(in_c, out_c, stride):
            return nn.Sequential(
                _ConvBNReLU(in_c, in_c, 3, stride, groups=in_c, relu6=False),
                _ConvBNReLU(in_c, out_c, 1, 1, relu6=False))

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
            [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNReLU(3, c(32), 3, 2, relu6=False)]
        for in_c, out_c, s in cfg:
            layers.append(dw_sep(c(in_c), c(out_c), s))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride, groups=hidden),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]

        def c(ch):
            return max(8, int(ch * scale + 4) // 8 * 8)

        in_c = c(32)
        layers = [_ConvBNReLU(3, in_c, 3, 2)]
        for t, ch, n, s in cfg:
            out_c = c(ch)
            for i in range(n):
                layers.append(InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        last = c(1280) if scale > 1.0 else 1280
        layers.append(_ConvBNReLU(in_c, last, 1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained: no egress; load local ckpt")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained: no egress; load local ckpt")
    return MobileNetV2(scale=scale, **kwargs)
