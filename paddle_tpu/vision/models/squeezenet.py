"""SqueezeNet — python/paddle/vision/models/squeezenet.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from ... import nn
from ... import ops


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, e1_c, e3_c):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.expand1 = nn.Conv2D(squeeze_c, e1_c, 1)
        self.expand3 = nn.Conv2D(squeeze_c, e3_c, 3, padding=1)

    def forward(self, x):
        s = nn.functional.relu(self.squeeze(x))
        return ops.concat([nn.functional.relu(self.expand1(s)),
                           nn.functional.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, padding=0),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        else:
            raise ValueError(f"unsupported SqueezeNet version {version}")
        self.classifier_conv = nn.Conv2D(512, num_classes, 1)
        self.dropout = nn.Dropout(0.5)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        x = nn.functional.relu(self.classifier_conv(self.dropout(x)))
        if self.with_pool:
            x = self.pool(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights unavailable offline "
            "(paddle_tpu/vision/models/squeezenet.py)")
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights unavailable offline "
            "(paddle_tpu/vision/models/squeezenet.py)")
    return SqueezeNet(version="1.1", **kwargs)
