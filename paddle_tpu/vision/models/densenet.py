"""DenseNet — python/paddle/vision/models/densenet.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from ... import nn
from ... import ops


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(nn.functional.relu(self.norm1(x)))
        out = self.conv2(nn.functional.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return ops.concat([x, out], axis=1)


class _Transition(nn.Sequential):
    def __init__(self, in_c, out_c):
        super().__init__(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.AvgPool2D(2, 2))


_CFG = {121: (64, 32, [6, 12, 24, 16]),
        161: (96, 48, [6, 12, 36, 24]),
        169: (64, 32, [6, 12, 32, 32]),
        201: (64, 32, [6, 12, 48, 32]),
        264: (64, 32, [6, 12, 64, 48])}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        assert layers in _CFG, f"DenseNet-{layers} not supported"
        init_c, growth, blocks = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, 2, padding=1)]
        c = init_c
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if i != len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights unavailable offline "
            "(paddle_tpu/vision/models/densenet.py)")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
