"""MobileNetV3 — python/paddle/vision/models/mobilenetv3.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SE(nn.Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        hidden = _make_divisible(channels // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, hidden, 1)
        self.fc2 = nn.Conv2D(hidden, channels, 1)

    def forward(self, x):
        s = self.pool(x)
        s = nn.functional.relu(self.fc1(s))
        s = nn.functional.hardsigmoid(self.fc2(s))
        return x * s


class _Bneck(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        Act = nn.Hardswish if act == "hardswish" else nn.ReLU
        if exp_c != in_c:
            layers += [nn.Conv2D(in_c, exp_c, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_c), Act()]
        layers += [nn.Conv2D(exp_c, exp_c, kernel, stride=stride,
                             padding=kernel // 2, groups=exp_c,
                             bias_attr=False),
                   nn.BatchNorm2D(exp_c), Act()]
        if use_se:
            layers.append(_SE(exp_c))
        layers += [nn.Conv2D(exp_c, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [nn.Conv2D(3, in_c, 3, stride=2, padding=1,
                            bias_attr=False),
                  nn.BatchNorm2D(in_c), nn.Hardswish()]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            layers.append(_Bneck(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        final_exp = _make_divisible(cfg[-1][1] * scale)
        layers += [nn.Conv2D(in_c, final_exp, 1, bias_attr=False),
                   nn.BatchNorm2D(final_exp), nn.Hardswish()]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(final_exp, last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


_LARGE = [
    # k, exp, out, SE, act, stride
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]

_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights unavailable offline "
            "(paddle_tpu/vision/models/mobilenetv3.py)")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights unavailable offline "
            "(paddle_tpu/vision/models/mobilenetv3.py)")
    return MobileNetV3Small(scale=scale, **kwargs)
