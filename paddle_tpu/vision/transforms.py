"""Vision transforms — python/paddle/vision/transforms/ parity
(upstream-canonical, unverified — SURVEY.md §0). Numpy/PIL-free: operates on
HWC numpy arrays (PIL accepted if available). Host-side preprocessing stays on
CPU by design — device work starts at the batch boundary."""
from __future__ import annotations

import numbers
import random as pyrandom
from typing import List, Sequence

import numpy as np


def _to_hwc_array(img):
    if isinstance(img, np.ndarray):
        return img
    # PIL image duck-typing
    return np.asarray(img)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(_to_hwc_array(img))


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        out = img.astype(np.float32) / 255.0 if img.dtype == np.uint8 \
            else img.astype(np.float32)
        if self.data_format == "CHW":
            out = out.transpose(2, 0, 1)
        return out


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = img.astype(np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp

        h, w = self.size
        method = {"bilinear": "linear", "nearest": "nearest",
                  "bicubic": "cubic"}[self.interpolation]
        squeeze = img.ndim == 2
        if squeeze:
            img = img[:, :, None]
        out = np.asarray(jax.image.resize(
            jnp.asarray(img.astype(np.float32)), (h, w, img.shape[2]), method=method))
        if img.dtype == np.uint8:
            out = np.clip(out, 0, 255).astype(np.uint8)
        return out[:, :, 0] if squeeze else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


def _norm_padding4(p):
    """int | (lr, tb) | (l, t, r, b) → (l, t, r, b)."""
    if isinstance(p, (int, numbers.Integral)):
        return (p, p, p, p)
    p = tuple(p)
    if len(p) == 2:
        return (p[0], p[1], p[0], p[1])
    if len(p) == 4:
        return p
    raise ValueError(f"padding must be int, 2-tuple, or 4-tuple; got {p}")


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def _apply_image(self, img):
        if self.padding:
            l, t, r, b = _norm_padding4(self.padding)
            pads = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads, constant_values=self.fill)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            pads = [(0, max(th - h, 0)), (0, max(tw - w, 0))] + \
                [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads, constant_values=self.fill)
            h, w = img.shape[:2]
        if h < th or w < tw:
            raise ValueError(
                f"image ({h},{w}) smaller than crop {self.size}; pass "
                "pad_if_needed=True")
        i = pyrandom.randint(0, h - th)
        j = pyrandom.randint(0, w - tw)
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return img[::-1].copy()
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            ar = np.exp(pyrandom.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = pyrandom.randint(0, h - th)
                j = pyrandom.randint(0, w - tw)
                return self.resize._apply_image(img[i:i + th, j:j + tw])
        return self.resize._apply_image(CenterCrop(min(h, w))._apply_image(img))


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = _norm_padding4(padding)
        self.fill = fill

    def _apply_image(self, img):
        l, t, r, b = self.padding
        pads = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
        return np.pad(img, pads, constant_values=self.fill)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        f = 1 + pyrandom.uniform(-self.value, self.value)
        return np.clip(img.astype(np.float32) * f, 0,
                       255 if img.dtype == np.uint8 else np.inf).astype(img.dtype)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast

    def _apply_image(self, img):
        out = img.astype(np.float32)
        if self.brightness:
            out = out * (1 + pyrandom.uniform(-self.brightness, self.brightness))
        if self.contrast:
            mean = out.mean()
            out = (out - mean) * (1 + pyrandom.uniform(-self.contrast, self.contrast)) + mean
        hi = 255 if img.dtype == np.uint8 else np.inf
        return np.clip(out, 0, hi).astype(img.dtype)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(_to_hwc_array(img))


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _to_hwc_array(img)[:, ::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)
