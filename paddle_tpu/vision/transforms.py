"""Vision transforms — python/paddle/vision/transforms/ parity
(upstream-canonical, unverified — SURVEY.md §0). Numpy/PIL-free: operates on
HWC numpy arrays (PIL accepted if available). Host-side preprocessing stays on
CPU by design — device work starts at the batch boundary."""
from __future__ import annotations

import numbers
import random as pyrandom
from typing import List, Sequence

import numpy as np


def _to_hwc_array(img):
    if isinstance(img, np.ndarray):
        return img
    # PIL image duck-typing
    return np.asarray(img)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(_to_hwc_array(img))


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        out = img.astype(np.float32) / 255.0 if img.dtype == np.uint8 \
            else img.astype(np.float32)
        if self.data_format == "CHW":
            out = out.transpose(2, 0, 1)
        return out


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = img.astype(np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp

        h, w = self.size
        method = {"bilinear": "linear", "nearest": "nearest",
                  "bicubic": "cubic"}[self.interpolation]
        squeeze = img.ndim == 2
        if squeeze:
            img = img[:, :, None]
        out = np.asarray(jax.image.resize(
            jnp.asarray(img.astype(np.float32)), (h, w, img.shape[2]), method=method))
        if img.dtype == np.uint8:
            out = np.clip(out, 0, 255).astype(np.uint8)
        return out[:, :, 0] if squeeze else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


def _norm_padding4(p):
    """int | (lr, tb) | (l, t, r, b) → (l, t, r, b)."""
    if isinstance(p, (int, numbers.Integral)):
        return (p, p, p, p)
    p = tuple(p)
    if len(p) == 2:
        return (p[0], p[1], p[0], p[1])
    if len(p) == 4:
        return p
    raise ValueError(f"padding must be int, 2-tuple, or 4-tuple; got {p}")


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def _apply_image(self, img):
        if self.padding:
            l, t, r, b = _norm_padding4(self.padding)
            pads = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads, constant_values=self.fill)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            pads = [(0, max(th - h, 0)), (0, max(tw - w, 0))] + \
                [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads, constant_values=self.fill)
            h, w = img.shape[:2]
        if h < th or w < tw:
            raise ValueError(
                f"image ({h},{w}) smaller than crop {self.size}; pass "
                "pad_if_needed=True")
        i = pyrandom.randint(0, h - th)
        j = pyrandom.randint(0, w - tw)
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return img[::-1].copy()
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            ar = np.exp(pyrandom.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = pyrandom.randint(0, h - th)
                j = pyrandom.randint(0, w - tw)
                return self.resize._apply_image(img[i:i + th, j:j + tw])
        return self.resize._apply_image(CenterCrop(min(h, w))._apply_image(img))


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = _norm_padding4(padding)
        self.fill = fill

    def _apply_image(self, img):
        l, t, r, b = self.padding
        pads = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
        return np.pad(img, pads, constant_values=self.fill)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        f = 1 + pyrandom.uniform(-self.value, self.value)
        return np.clip(img.astype(np.float32) * f, 0,
                       255 if img.dtype == np.uint8 else np.inf).astype(img.dtype)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast

    def _apply_image(self, img):
        out = img.astype(np.float32)
        if self.brightness:
            out = out * (1 + pyrandom.uniform(-self.brightness, self.brightness))
        if self.contrast:
            mean = out.mean()
            out = (out - mean) * (1 + pyrandom.uniform(-self.contrast, self.contrast)) + mean
        hi = 255 if img.dtype == np.uint8 else np.inf
        return np.clip(out, 0, hi).astype(img.dtype)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(_to_hwc_array(img))


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _to_hwc_array(img)[:, ::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


# ---------------------------------------------------------------------------
# Functional surface — paddle.vision.transforms functional parity
# (python/paddle/vision/transforms/functional.py, upstream-canonical,
# unverified — SURVEY.md §0). Numpy-array HWC images in/out, like the
# reference's numpy backend; the class transforms above compose these.
# ---------------------------------------------------------------------------

def vflip(img):
    return _to_hwc_array(img)[::-1].copy()


def crop(img, top, left, height, width):
    return _to_hwc_array(img)[top:top + height, left:left + width].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    a = _to_hwc_array(img)
    l, t, r, b = _norm_padding4(padding)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    return np.pad(a, ((t, b), (l, r), (0, 0)), mode=mode, **kw)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate by `angle` degrees counter-clockwise about the center
    (nearest-neighbor resampling; the reference's PIL backend default)."""
    orig = _to_hwc_array(img)
    a = orig.astype(np.float32)
    h, w = a.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else \
        (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    xs = cos * (xx - cx) + sin * (yy - cy) + cx
    ys = -sin * (xx - cx) + cos * (yy - cy) + cy
    xi = np.round(xs).astype(np.int64)
    yi = np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out = np.full_like(a, float(fill))
    out[valid] = a[yi[valid], xi[valid]]
    return out.astype(orig.dtype)


def adjust_brightness(img, brightness_factor):
    orig = _to_hwc_array(img)
    a = orig.astype(np.float32)
    hi = 255.0 if np.issubdtype(orig.dtype, np.integer) else 1.0
    return np.clip(a * brightness_factor, 0, hi).astype(orig.dtype)


def adjust_contrast(img, contrast_factor):
    orig = _to_hwc_array(img)
    a = orig.astype(np.float32)
    mean = a.mean()
    hi = 255.0 if np.issubdtype(orig.dtype, np.integer) else 1.0
    return np.clip(mean + contrast_factor * (a - mean), 0, hi).astype(
        orig.dtype)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) via RGB<->HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} not in [-0.5, 0.5]")
    orig = _to_hwc_array(img)
    hi = 255.0 if np.issubdtype(orig.dtype, np.integer) else 1.0
    a = orig.astype(np.float32) / hi
    r, g, b = a[..., 0], a[..., 1], a[..., 2]
    mx, mn = a.max(-1), a.min(-1)
    d = mx - mn
    h = np.zeros_like(mx)
    mask = d > 0
    rm = mask & (mx == r)
    gm = mask & (mx == g) & ~rm
    bm = mask & ~rm & ~gm
    h[rm] = ((g - b)[rm] / d[rm]) % 6
    h[gm] = (b - r)[gm] / d[gm] + 2
    h[bm] = (r - g)[bm] / d[bm] + 4
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, d / np.maximum(mx, 1e-12), 0)
    v = mx
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(np.int64) % 6
    rgb = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q])], axis=-1)
    return (rgb * hi).astype(orig.dtype)


def to_grayscale(img, num_output_channels=1):
    orig = _to_hwc_array(img)
    a = orig.astype(np.float32)
    gray = 0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2]
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return out.astype(orig.dtype)


def erase(img, i, j, h, w, v, inplace=False):
    """paddle.vision.transforms.erase: fill region [i:i+h, j:j+w] with v.
    Tensor input stays CHW tensor (reference semantics); arrays are HWC."""
    from ..core.tensor import Tensor
    if isinstance(img, Tensor):
        import jax.numpy as jnp
        data = img._data
        val = jnp.asarray(v, data.dtype)
        patch = jnp.broadcast_to(val, (data.shape[0], h, w))
        new = data.at[:, i:i + h, j:j + w].set(patch)
        if inplace:
            img._data = new
            return img
        return Tensor(new)
    a = _to_hwc_array(img)
    out = a if inplace else a.copy()
    out[i:i + h, j:j + w] = np.broadcast_to(
        np.asarray(v, a.dtype), (h, w, a.shape[2]))
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine transform: rotate(angle) + translate + scale + shear, about
    the image center (inverse-map nearest resampling)."""
    orig = _to_hwc_array(img)
    a = orig.astype(np.float32)
    h, w = a.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else \
        (center[1], center[0])
    rad = np.deg2rad(angle)
    sx = np.deg2rad(shear[0] if isinstance(shear, (list, tuple)) else shear)
    sy = np.deg2rad(shear[1] if isinstance(shear, (list, tuple))
                    and len(shear) > 1 else 0.0)
    # forward matrix M = R(angle) @ Shear @ diag(scale); sample via M^-1
    m = np.array([
        [np.cos(rad + sy) / np.cos(sy),
         -np.cos(rad + sy) * np.tan(sx) / np.cos(sy) - np.sin(rad)],
        [np.sin(rad + sy) / np.cos(sy),
         -np.sin(rad + sy) * np.tan(sx) / np.cos(sy) + np.cos(rad)],
    ]) * scale
    minv = np.linalg.inv(m)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    dx = xx - cx - translate[0]
    dy = yy - cy - translate[1]
    xs = minv[0, 0] * dx + minv[0, 1] * dy + cx
    ys = minv[1, 0] * dx + minv[1, 1] * dy + cy
    xi, yi = np.round(xs).astype(np.int64), np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out = np.full_like(a, float(fill))
    out[valid] = a[yi[valid], xi[valid]]
    return out.astype(orig.dtype)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective transform mapping startpoints -> endpoints (4 corner
    pairs), inverse-map nearest resampling."""
    orig = _to_hwc_array(img)
    a = orig.astype(np.float32)
    h, w = a.shape[:2]
    # solve the 8-dof homography sending endpoints -> startpoints
    A, bvec = [], []
    for (ex, ey), (sx_, sy_) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx_ * ex, -sx_ * ey])
        bvec.append(sx_)
        A.append([0, 0, 0, ex, ey, 1, -sy_ * ex, -sy_ * ey])
        bvec.append(sy_)
    coef = np.linalg.solve(np.asarray(A, np.float64),
                           np.asarray(bvec, np.float64))
    hm = np.append(coef, 1.0).reshape(3, 3)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
    den = hm[2, 0] * xx + hm[2, 1] * yy + hm[2, 2]
    xs = (hm[0, 0] * xx + hm[0, 1] * yy + hm[0, 2]) / den
    ys = (hm[1, 0] * xx + hm[1, 1] * yy + hm[1, 2]) / den
    xi, yi = np.round(xs).astype(np.int64), np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out = np.full_like(a, float(fill))
    out[valid] = a[yi[valid], xi[valid]]
    return out.astype(orig.dtype)


def adjust_saturation(img, saturation_factor):
    orig = _to_hwc_array(img)
    a = orig.astype(np.float32)
    gray = (0.299 * a[..., 0] + 0.587 * a[..., 1]
            + 0.114 * a[..., 2])[..., None]
    hi = 255.0 if np.issubdtype(orig.dtype, np.integer) else 1.0
    return np.clip(gray + saturation_factor * (a - gray), 0, hi).astype(
        orig.dtype)


# ---------------------------------------------------------------------------
# Round-3: transform classes over the functional surface
# (python/paddle/vision/transforms/transforms.py parity). House contract:
# implement _apply_image (BaseTransform.__call__ owns the HWC conversion)
# and draw randomness from pyrandom, like every other class here — one
# seedable RNG source for the whole pipeline.
# ---------------------------------------------------------------------------

class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError(f"contrast value must be >= 0, got {value}")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        # reference clamps the low end at 0 — no contrast inversion
        f = pyrandom.uniform(max(0.0, 1.0 - self.value), 1.0 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError(f"saturation value must be >= 0, got {value}")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = pyrandom.uniform(max(0.0, 1.0 - self.value), 1.0 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError(
                f"hue value must be in [0, 0.5], got {value}")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, pyrandom.uniform(-self.value, self.value))


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if expand:
            raise NotImplementedError(
                "RandomRotation(expand=True): canvas growth is not "
                "implemented — rotate() keeps the input extent "
                "(paddle_tpu/vision/transforms.py)")
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, numbers.Number) else tuple(degrees)
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = pyrandom.uniform(*self.degrees)
        return rotate(img, angle, center=self.center, fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, numbers.Number) else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        h, w = img.shape[:2]
        angle = pyrandom.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = pyrandom.uniform(-self.translate[0], self.translate[0]) * w
            ty = pyrandom.uniform(-self.translate[1], self.translate[1]) * h
        sc = 1.0 if self.scale is None else pyrandom.uniform(*self.scale)
        if self.shear is None:
            sh = 0.0
        elif isinstance(self.shear, numbers.Number):
            sh = pyrandom.uniform(-self.shear, self.shear)
        elif len(self.shear) == 4:   # [min_x, max_x, min_y, max_y]
            sh = (pyrandom.uniform(self.shear[0], self.shear[1]),
                  pyrandom.uniform(self.shear[2], self.shear[3]))
        else:
            sh = pyrandom.uniform(*self.shear)
        return affine(img, angle, (tx, ty), sc, sh, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if pyrandom.random() >= self.prob:
            return img
        h, w = img.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        # reference semantics: corners displace strictly INTO the image
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        signs = [(1, 1), (-1, 1), (-1, -1), (1, -1)]
        end = [(x + sx * pyrandom.randint(0, max(dx, 0)),
                y + sy * pyrandom.randint(0, max(dy, 0)))
               for (x, y), (sx, sy) in zip(start, signs)]
        return perspective(img, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def __call__(self, img):
        # CHW Tensors keep their type — erase() has a dedicated Tensor
        # branch; everything else takes the HWC array path
        from ..core.tensor import Tensor
        if isinstance(img, Tensor):
            c, h, w = img.shape[-3], img.shape[-2], img.shape[-1]
            box = self._pick(h, w)
            if box is None:
                return img
            i, j, eh, ew = box
            v = self._fill_value((c, eh, ew), img.numpy().dtype)
            return erase(img, i, j, eh, ew, v, inplace=self.inplace)
        return super().__call__(img)

    def _fill_value(self, shape, dtype):
        if isinstance(self.value, str):
            if self.value != "random":
                raise ValueError(f"RandomErasing value {self.value!r}: "
                                 "'random' or a number/sequence")
            if np.issubdtype(np.dtype(dtype), np.integer):
                return np.random.randint(0, 256, shape).astype(dtype)
            return np.random.standard_normal(shape).astype(dtype)
        return self.value

    def _pick(self, h, w):
        if pyrandom.random() >= self.prob:
            return None
        area = h * w
        for _ in range(10):
            target = pyrandom.uniform(*self.scale) * area
            log_lo, log_hi = np.log(self.ratio[0]), np.log(self.ratio[1])
            ar = np.exp(pyrandom.uniform(log_lo, log_hi))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if 0 < eh < h and 0 < ew < w:
                # INCLUSIVE bounds: edge-flush placements are reachable
                return (pyrandom.randint(0, h - eh),
                        pyrandom.randint(0, w - ew), eh, ew)
        return None

    def _apply_image(self, img):
        box = self._pick(img.shape[0], img.shape[1])
        if box is None:
            return img
        i, j, eh, ew = box
        v = self._fill_value((eh, ew, img.shape[2]), img.dtype)
        return erase(img, i, j, eh, ew, v, inplace=self.inplace)
