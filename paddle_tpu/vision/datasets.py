"""Vision datasets — python/paddle/vision/datasets/ parity (upstream-canonical,
unverified — SURVEY.md §0). Zero-egress environment: download paths raise with
instructions; FakeData (paddle-parity: paddle.vision.datasets has none, but the
reference test-suites synthesize data the same way) serves as the offline
stand-in for smoke tests."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io.dataset import Dataset


class FakeData(Dataset):
    """Synthetic image classification dataset (offline smoke tests)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.standard_normal(self.image_shape).astype(np.float32)
        label = int(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return self.size


class MNIST(Dataset):
    """MNIST from local idx files (no download — zero egress)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2"):
        if image_path is None or label_path is None or \
                not os.path.exists(image_path):
            raise RuntimeError(
                "MNIST download unavailable (zero-egress environment); place "
                "idx files locally and pass image_path/label_path "
                "(paddle_tpu/vision/datasets.py)")
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    def _load(self, image_path, label_path):
        with gzip.open(image_path, "rb") if image_path.endswith(".gz") else \
                open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") if label_path.endswith(".gz") else \
                open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], dtype=np.int64)

    def __len__(self):
        return len(self.images)


FashionMNIST = MNIST


class Cifar10(Dataset):
    """CIFAR-10 from a local python-pickle tarball (no download)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                "Cifar10 download unavailable (zero-egress environment); pass "
                "a local cifar-10-python.tar.gz via data_file")
        self.transform = transform
        names, label_key = self._members(mode)
        xs, ys = [], []
        with tarfile.open(data_file) as tar:
            for m in tar.getmembers():
                if any(m.name.endswith(n) for n in names):
                    d = pickle.load(tar.extractfile(m), encoding="bytes")
                    xs.append(d[b"data"])
                    ys.extend(d[label_key])
        if not xs:
            raise RuntimeError(
                f"no {names} members found in {data_file} — wrong archive?")
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        self.labels = np.asarray(ys, dtype=np.int64)

    @staticmethod
    def _members(mode):
        names = [f"data_batch_{i}" for i in range(1, 6)] if mode == "train" \
            else ["test_batch"]
        return names, b"labels"

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    """CIFAR-100 layout differs: members 'train'/'test', key b'fine_labels'."""

    @staticmethod
    def _members(mode):
        return (["train"] if mode == "train" else ["test"]), b"fine_labels"


class DatasetFolder(Dataset):
    """ImageFolder-style directory dataset (class-per-subdir)."""

    IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or self.IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError(
                f"no loader for {path}: PIL unavailable; use .npy files or "
                "pass loader=") from e

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


class Flowers(Dataset):
    """Oxford 102 Flowers from the upstream triple (images tgz +
    imagelabels.mat + setid.mat) — paddle.vision.datasets.Flowers parity,
    local files only (zero egress)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend="pil"):
        for f, n in ((data_file, "data_file"), (label_file, "label_file"),
                     (setid_file, "setid_file")):
            if f is None or not os.path.exists(f):
                raise RuntimeError(
                    f"Flowers download unavailable (zero-egress "
                    f"environment); pass {n}= pointing at the upstream "
                    f"archive (paddle_tpu/vision/datasets.py)")
        import scipy.io as sio
        labels = sio.loadmat(label_file)["labels"].reshape(-1)
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key].reshape(-1)
        self.labels = labels
        self.transform = transform
        self._tar = tarfile.open(data_file)
        self._members = {os.path.basename(m.name): m
                         for m in self._tar.getmembers() if m.isfile()}

    def __getitem__(self, idx):
        import io as _io
        from PIL import Image
        flower_id = int(self.indexes[idx])
        member = self._members[f"image_{flower_id:05d}.jpg"]
        img = np.asarray(Image.open(
            _io.BytesIO(self._tar.extractfile(member).read())
        ).convert("RGB"))
        label = np.asarray(int(self.labels[flower_id - 1]), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs from the upstream devkit tar —
    paddle.vision.datasets.VOC2012 parity ((image, label-mask) uint8
    arrays), local files only."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="pil"):
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                "VOC2012 download unavailable (zero-egress environment); "
                "pass data_file= pointing at the upstream devkit tar "
                "(paddle_tpu/vision/datasets.py)")
        self.transform = transform
        self._tar = tarfile.open(data_file)
        names = self._tar.getnames()
        seg_list = next(n for n in names if n.endswith(
            f"ImageSets/Segmentation/{'train' if mode == 'train' else 'val'}"
            f".txt"))
        ids = self._tar.extractfile(seg_list).read().decode().split()
        base = seg_list.split("ImageSets/")[0]
        self.pairs = [(f"{base}JPEGImages/{i}.jpg",
                       f"{base}SegmentationClass/{i}.png") for i in ids]

    def __getitem__(self, idx):
        import io as _io
        from PIL import Image
        ipath, lpath = self.pairs[idx]
        img = np.asarray(Image.open(
            _io.BytesIO(self._tar.extractfile(ipath).read())).convert("RGB"))
        label = np.asarray(Image.open(
            _io.BytesIO(self._tar.extractfile(lpath).read())))
        if self.transform is not None:
            img = self.transform(img)
        return img, label.astype(np.uint8)

    def __len__(self):
        return len(self.pairs)
