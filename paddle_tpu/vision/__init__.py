"""paddle_tpu.vision — python/paddle/vision/ parity (upstream-canonical,
unverified — SURVEY.md §0)."""
from . import transforms  # noqa: F401
from . import models  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .datasets import (FakeData, MNIST, Cifar10, Cifar100, DatasetFolder,  # noqa: F401
                       ImageFolder, Flowers, VOC2012)
from .models import *  # noqa: F401,F403
