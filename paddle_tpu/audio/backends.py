"""paddle.audio.backends — wav I/O (load/save/info) + backend registry.

Reference parity: python/paddle/audio/backends/ (init_backend.py's
get_current_audio_backend/list_available_backends/set_backend and
wave_backend.py's load/save/info over the stdlib wave module —
upstream-canonical, unverified, SURVEY.md §0). The default (and, in this
zero-egress build, only) backend is the stdlib-wave PCM backend, exactly
like the reference's fallback when paddleaudio is not installed; the
registry shape is kept so a soundfile-style backend can slot in.
"""
from __future__ import annotations

import dataclasses
import wave as _wave

import numpy as _np

from ..core.tensor import Tensor

_BACKENDS = ["wave"]
_current = "wave"


def list_available_backends():
    """Names of usable audio I/O backends (parity:
    paddle.audio.backends.list_available_backends)."""
    return list(_BACKENDS)


def get_current_audio_backend():
    return _current


def set_backend(backend_name: str):
    global _current
    if backend_name not in _BACKENDS:
        raise NotImplementedError(
            f"backend {backend_name!r} not available; choices: {_BACKENDS} "
            "(the paddleaudio soundfile backend needs an external package — "
            "zero-egress build ships the stdlib wave backend)")
    _current = backend_name


@dataclasses.dataclass
class AudioInfo:
    """Parity with paddle.audio.backends' AudioInfo."""
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str


def info(filepath: str) -> AudioInfo:
    with _wave.open(filepath, "rb") as f:
        width = f.getsampwidth()
        return AudioInfo(
            sample_rate=f.getframerate(), num_samples=f.getnframes(),
            num_channels=f.getnchannels(), bits_per_sample=8 * width,
            # wav width-1 is unsigned PCM — matches _decode_pcm's reading
            encoding="PCM_U" if width == 1 else "PCM_S")


def _decode_pcm(raw: bytes, width: int, channels: int, normalize: bool):
    if width == 2:
        x = _np.frombuffer(raw, _np.int16)
        scale = 32768.0
    elif width == 1:  # unsigned 8-bit PCM
        x = _np.frombuffer(raw, _np.uint8).astype(_np.int16) - 128
        scale = 128.0
    elif width == 4:
        x = _np.frombuffer(raw, _np.int32)
        scale = 2147483648.0
    else:
        raise ValueError(f"unsupported PCM sample width {width}")
    x = x.reshape(-1, channels).T  # [C, T]
    if normalize:
        return (x.astype(_np.float32) / scale, _np.float32)
    return (x, x.dtype)


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Read a PCM wav → (Tensor waveform, sample_rate). Normalized f32 in
    [-1, 1) by default; channels_first gives [C, T] (the reference's
    wave_backend.load contract)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
        data, _ = _decode_pcm(raw, f.getsampwidth(), f.getnchannels(),
                              normalize)
    if not channels_first:
        data = data.T
    return Tensor(data), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         bits_per_sample: int = 16):
    """Write a [C, T] (or [T, C]) float waveform in [-1, 1] as PCM wav."""
    if bits_per_sample != 16:
        raise NotImplementedError(
            "wave backend writes 16-bit PCM (parity: the reference's "
            "wave_backend.save)")
    x = _np.asarray(src.numpy() if isinstance(src, Tensor) else src)
    if channels_first:
        x = x.T  # → [T, C]
    pcm = _np.clip(_np.asarray(x, _np.float64) * 32768.0,
                   -32768, 32767).astype("<i2")
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(pcm.shape[1] if pcm.ndim > 1 else 1)
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
