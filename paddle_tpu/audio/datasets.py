"""paddle.audio.datasets — TESS and ESC-50 (local-archive loaders).

Reference parity: python/paddle/audio/datasets/{tess,esc50}.py
(upstream-canonical, unverified — SURVEY.md §0): TESS labels come from
the `..._emotion.wav` filename suffix with an n_folds/split train/dev
partition; ESC-50 labels and folds come from meta/esc50.csv, with
`split` naming the held-out fold. Zero-egress build: archives are not
downloaded — pass the upstream zip via data_file= (the same pattern as
the text/vision dataset zoo; tests build synthetic archives in the
upstream layouts). feat_type composes the paddle.audio.features layers.
"""
from __future__ import annotations

import io as _io
import os as _os
import posixpath as _pp
import wave as _wave
import zipfile as _zipfile

import numpy as _np

from ..io.dataset import Dataset as _Dataset

_FEATS = ("raw", "spectrogram", "melspectrogram", "logmelspectrogram",
          "mfcc")


def _need(data_file, cls):
    if data_file is None or not _os.path.exists(data_file):
        raise RuntimeError(
            f"{cls} download unavailable (zero-egress environment); place "
            f"the upstream archive locally and pass data_file= "
            f"(paddle_tpu/audio/datasets.py)")


def _read_wav(buf: bytes):
    from .backends import _decode_pcm
    with _wave.open(_io.BytesIO(buf), "rb") as f:
        raw = f.readframes(f.getnframes())
        x, _ = _decode_pcm(raw, f.getsampwidth(), f.getnchannels(),
                           normalize=True)          # [C, T], width 1/2/4
        return x.mean(axis=0), f.getframerate()


class _AudioDataset(_Dataset):
    """Shared (waveform | feature, label) plumbing."""

    def __init__(self, feat_type, feat_kwargs):
        if feat_type not in _FEATS:
            raise ValueError(f"feat_type {feat_type!r} not in {_FEATS}")
        self.feat_type = feat_type
        self._feat = None
        if feat_type != "raw":
            from ..audio.features import (MFCC, LogMelSpectrogram,
                                          MelSpectrogram, Spectrogram)
            cls = {"spectrogram": Spectrogram,
                   "melspectrogram": MelSpectrogram,
                   "logmelspectrogram": LogMelSpectrogram,
                   "mfcc": MFCC}[feat_type]
            self._feat = cls(**(feat_kwargs or {}))

    def _emit(self, wav: _np.ndarray, label: int):
        if self._feat is None:
            return wav, _np.int64(label)
        from ..core.tensor import Tensor
        out = self._feat(Tensor(wav[None, :]))
        return out.numpy()[0], _np.int64(label)

    def __len__(self):
        return len(self._labels)

    def __getitem__(self, idx):
        # lazy: decode one clip per access (the real ESC-50 is ~1.7 GB
        # of f32 if decoded wholesale at construction)
        with _zipfile.ZipFile(self._data_file) as zf:
            wav, _ = _read_wav(zf.read(self._names[idx]))
        return self._emit(wav, self._labels[idx])


class TESS(_AudioDataset):
    """Toronto Emotional Speech Set: 7-way emotion from the filename
    suffix (`OAF_back_angry.wav` → angry), n_folds round-robin
    train/dev split like the reference."""

    label_list = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                  "sad"]

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_file=None, **feat_kwargs):
        super().__init__(feat_type, feat_kwargs)
        _need(data_file, "TESS")
        if not 1 <= split <= n_folds:
            raise ValueError(f"split {split} outside 1..{n_folds}")
        self._data_file = data_file
        keep_names, labels = [], []
        with _zipfile.ZipFile(data_file) as zf:
            names = sorted(n for n in zf.namelist()
                           if n.lower().endswith(".wav"))
        for i, name in enumerate(names):
            fold = i % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if not keep:
                continue
            emotion = _pp.basename(name).rsplit(".", 1)[0] \
                .split("_")[-1].lower()
            if emotion not in self.label_list:
                continue
            keep_names.append(name)
            labels.append(self.label_list.index(emotion))
        self._names, self._labels = keep_names, labels


class ESC50(_AudioDataset):
    """ESC-50 environmental sounds: labels + folds from meta/esc50.csv;
    `split` is the held-out fold (the reference's scheme)."""

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_file=None, **feat_kwargs):
        super().__init__(feat_type, feat_kwargs)
        _need(data_file, "ESC50")
        self._data_file = data_file
        keep_names, labels = [], []
        with _zipfile.ZipFile(data_file) as zf:
            meta_name = next(n for n in zf.namelist()
                             if n.endswith("esc50.csv"))
            rows = zf.read(meta_name).decode("utf-8").strip().split("\n")
        header = rows[0].split(",")
        fn_i, fold_i, tgt_i = (header.index(c)
                               for c in ("filename", "fold", "target"))
        # zip members are always '/'-separated; the audio dir is the
        # meta dir's SIBLING (replace only the final path component)
        audio_dir = _pp.join(_pp.dirname(_pp.dirname(meta_name)), "audio")
        folds = {int(r.split(",")[fold_i]) for r in rows[1:]}
        if split not in folds:
            raise ValueError(f"split {split} not among csv folds "
                             f"{sorted(folds)}")
        for row in rows[1:]:
            cols = row.split(",")
            fold, target = int(cols[fold_i]), int(cols[tgt_i])
            keep = (fold != split) if mode == "train" else (fold == split)
            if not keep:
                continue
            keep_names.append(_pp.join(audio_dir, cols[fn_i]))
            labels.append(target)
        self._names, self._labels = keep_names, labels
