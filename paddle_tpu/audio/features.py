"""paddle.audio.features — Spectrogram / MelSpectrogram / LogMel / MFCC."""
from __future__ import annotations

from .. import nn
from ..ops._registry import eager
from ..signal import stft
from . import functional as AF

import jax.numpy as jnp


class Spectrogram(nn.Layer):
    def __init__(self, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=1.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", AF.get_window(window, self.win_length, dtype=dtype))

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    self.window, center=self.center, pad_mode=self.pad_mode)
        return eager(lambda s: jnp.abs(s) ** self.power, (spec,), {},
                     name="spectrogram_power")


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.register_buffer("fbank_matrix", AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype))

    def forward(self, x):
        spec = self._spectrogram(x)  # [..., freq, time]
        return eager(lambda fb, s: jnp.matmul(fb, s),
                     (self.fbank_matrix, spec), {}, name="mel_fbank")


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                   power, center, pad_mode, n_mels, f_min,
                                   f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self._mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer("dct_matrix",
                             AF.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        logmel = self._log_mel(x)  # [..., n_mels, time]
        return eager(
            lambda d, m: jnp.swapaxes(
                jnp.matmul(jnp.swapaxes(m, -2, -1), d), -2, -1),
            (self.dct_matrix, logmel), {}, name="mfcc_dct")
