"""paddle.audio — audio feature extraction (spectrograms, mel, MFCC).

Reference parity: python/paddle/audio/ (features/layers.py Spectrogram/
MelSpectrogram/LogMelSpectrogram/MFCC over paddle.signal.stft;
functional/functional.py hz_to_mel/mel_to_hz/compute_fbank_matrix/
create_dct; functional/window.py get_window; backends/ wave-based
load/save/info; datasets/ TESS + ESC50 — upstream-canonical,
unverified, SURVEY.md §0). TPU-native: everything composes from the
framework stft (batched FFT) + one fbank matmul — XLA fuses the chain.
"""
from . import backends, datasets, functional  # noqa: F401
from .backends import load, save, info  # noqa: F401
from .features import (Spectrogram, MelSpectrogram,  # noqa: F401
                       LogMelSpectrogram, MFCC)
