"""paddle.audio.functional — mel scales, filter banks, DCT, windows."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies",
           "compute_fbank_matrix", "create_dct", "get_window",
           "power_to_db"]


def hz_to_mel(freq, htk=False):
    scalar = not hasattr(freq, "__len__") and not isinstance(freq, Tensor)
    f = np.asarray(freq._data if isinstance(freq, Tensor) else freq,
                   np.float32)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:  # slaney
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, out)
    return float(out) if scalar else Tensor(jnp.asarray(out))


def mel_to_hz(mel, htk=False):
    scalar = not hasattr(mel, "__len__") and not isinstance(mel, Tensor)
    m = np.asarray(mel._data if isinstance(mel, Tensor) else mel, np.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)), out)
    return float(out) if scalar else Tensor(jnp.asarray(out))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = np.linspace(lo, hi, n_mels)
    return Tensor(jnp.asarray(
        np.asarray(mel_to_hz(mels, htk)._data), jnp.float32))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, n_fft//2 + 1] triangular mel filter bank."""
    if f_max is None:
        f_max = float(sr) / 2
    fft_freqs = np.linspace(0, float(sr) / 2, n_fft // 2 + 1)
    mel_f = np.asarray(mel_frequencies(n_mels + 2, f_min, f_max, htk)._data)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return Tensor(jnp.asarray(weights, np.dtype(dtype)))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II matrix (torchaudio/paddle layout)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct, np.dtype(dtype)))


_WINDOWS = {
    "hann": np.hanning, "hamming": np.hamming, "blackman": np.blackman,
    "bartlett": np.bartlett,
}


def get_window(window, win_length, fftbins=True, dtype="float32"):
    if isinstance(window, (tuple, list)):
        name, *args = window
    else:
        name, args = window, []
    n = win_length + 1 if fftbins else win_length
    if name in _WINDOWS:
        w = _WINDOWS[name](n)
    elif name in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    elif name == "gaussian":
        std = args[0] if args else win_length / 6.0
        m = np.arange(n) - (n - 1) / 2.0
        w = np.exp(-0.5 * (m / std) ** 2)
    elif name == "kaiser":
        beta = args[0] if args else 12.0
        w = np.kaiser(n, beta)
    else:
        try:  # full reference window zoo via scipy (taylor/tukey/bohman/...)
            from scipy.signal import get_window as _sp_get_window
            return Tensor(jnp.asarray(
                _sp_get_window(tuple(window) if args else name, win_length,
                               fftbins=fftbins), np.dtype(dtype)))
        except (ImportError, ValueError) as e:
            raise ValueError(f"unknown window {window!r}") from e
    if fftbins:
        w = w[:-1]
    return Tensor(jnp.asarray(w, np.dtype(dtype)))


def power_to_db(magnitude, ref_value=1.0, amin=1e-10, top_db=80.0):
    from ..ops._registry import eager

    def raw(x):
        db = 10.0 * jnp.log10(jnp.maximum(amin, x))
        db -= 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            db = jnp.maximum(db, jnp.max(db) - top_db)
        return db
    return eager(raw, (magnitude,), {}, name="power_to_db")
