"""paddle_tpu.mix — diffusion/multimodal model families.

Reference analog: PaddleMIX (DiT/SD3 recipes the reference's BASELINE
config 3 points at — out-of-repo domain suite, SURVEY.md §1 Lx row).
"""
from . import dit  # noqa: F401
