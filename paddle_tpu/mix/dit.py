"""DiT (Diffusion Transformer) — the BASELINE 'DiT/SD3' workload (config 3).

Reference analog: PaddleMIX's DiT implementation (facebookresearch DiT
architecture: patchify → AdaLN-Zero transformer blocks conditioned on
timestep+class embeddings → unpatchify; out-of-repo domain suite —
SURVEY.md §1 Lx row, §0 provenance).

TPU-native design (mirrors nlp/llama.py): functional params pytree, blocks
stacked on [L] and scanned, `param_specs` TP/FSDP table, bf16 compute /
f32 params. The conv+attention mix this workload exercises (SURVEY.md §7 M7
gate) comes from the patch-embed conv plus full self-attention blocks.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class DiTConfig:
    image_size: int = 32            # latent spatial size
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    depth: int = 28
    num_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    class_dropout_prob: float = 0.1
    learn_sigma: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def out_channels(self) -> int:
        return self.in_channels * (2 if self.learn_sigma else 1)

    @staticmethod
    def tiny(**over) -> "DiTConfig":
        base = dict(image_size=8, patch_size=2, in_channels=4,
                    hidden_size=64, depth=2, num_heads=4, num_classes=10)
        base.update(over)
        return DiTConfig(**base)

    @staticmethod
    def dit_xl_2(**over) -> "DiTConfig":
        base = dict(patch_size=2, hidden_size=1152, depth=28, num_heads=16)
        base.update(over)
        return DiTConfig(**base)


def init_params(key: jax.Array, cfg: DiTConfig) -> Dict[str, Any]:
    D, L = cfg.hidden_size, cfg.depth
    F = int(D * cfg.mlp_ratio)
    pc = cfg.patch_size * cfg.patch_size * cfg.in_channels
    pd = cfg.param_dtype
    ks = jax.random.split(key, 12)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(pd)

    return {
        "patch_embed_w": norm(ks[0], (pc, D)),
        "patch_embed_b": jnp.zeros((D,), pd),
        "pos_embed": norm(ks[1], (cfg.n_patches, D)),
        # timestep MLP (sinusoidal input dim 256 → D → D)
        "t_mlp1_w": norm(ks[2], (256, D)),
        "t_mlp1_b": jnp.zeros((D,), pd),
        "t_mlp2_w": norm(ks[3], (D, D)),
        "t_mlp2_b": jnp.zeros((D,), pd),
        # class embedding (+1 slot for classifier-free null label)
        "label_embed": norm(ks[4], (cfg.num_classes + 1, D)),
        "blocks": {
            # AdaLN-Zero: 6 modulation params per block from conditioning;
            # zero-init so each block starts as identity (DiT recipe)
            "ada_w": jnp.zeros((L, D, 6 * D), pd),
            "ada_b": jnp.zeros((L, 6 * D), pd),
            "qkv_w": norm(ks[5], (L, D, 3 * D)),
            "qkv_b": jnp.zeros((L, 3 * D), pd),
            "proj_w": norm(ks[6], (L, D, D)),
            "proj_b": jnp.zeros((L, D), pd),
            "mlp_in_w": norm(ks[7], (L, D, F)),
            "mlp_in_b": jnp.zeros((L, F), pd),
            "mlp_out_w": norm(ks[8], (L, F, D)),
            "mlp_out_b": jnp.zeros((L, D), pd),
        },
        "final_ada_w": jnp.zeros((D, 2 * D), pd),
        "final_ada_b": jnp.zeros((2 * D,), pd),
        "final_w": jnp.zeros(
            (D, cfg.patch_size * cfg.patch_size * cfg.out_channels), pd),
        "final_b": jnp.zeros(
            (cfg.patch_size * cfg.patch_size * cfg.out_channels,), pd),
    }


def param_specs(cfg: DiTConfig) -> Dict[str, Any]:
    return {
        "patch_embed_w": P("sharding", "mp"),
        "patch_embed_b": P("mp"),
        "pos_embed": P(None, "sharding"),
        "t_mlp1_w": P("sharding", "mp"),
        "t_mlp1_b": P("mp"),
        "t_mlp2_w": P("mp", "sharding"),
        "t_mlp2_b": P(None),
        "label_embed": P(None, "sharding"),
        "blocks": {
            "ada_w": P(None, "sharding", "mp"),
            "ada_b": P(None, "mp"),
            "qkv_w": P(None, "sharding", "mp"),
            "qkv_b": P(None, "mp"),
            "proj_w": P(None, "mp", "sharding"),
            "proj_b": P(None, None),
            "mlp_in_w": P(None, "sharding", "mp"),
            "mlp_in_b": P(None, "mp"),
            "mlp_out_w": P(None, "mp", "sharding"),
            "mlp_out_b": P(None, None),
        },
        "final_ada_w": P("sharding", "mp"),
        "final_ada_b": P("mp"),
        "final_w": P("sharding", None),
        "final_b": P(None),
    }


def batch_spec() -> P:
    """Latent batch [B, C, H, W] sharded over the data axes."""
    return P(("dp", "sharding"), None, None, None)


def timestep_embedding(t, dim=256, max_period=10000.0):
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _modulate(x, shift, scale):
    return x * (1 + scale[:, None]) + shift[:, None]


def _ln(x):  # elementwise-affine-free LN (DiT uses affine in modulation)
    # plain jnp on purpose: the fused layer_norm_train kernel measured
    # neutral here (adaLN cost is in the modulate chains, not the norm)
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype)


def _block(x, c, bp, cfg: DiTConfig):
    dt = cfg.dtype
    B, N, D = x.shape
    H, hd = cfg.num_heads, cfg.head_dim
    mods = c @ bp["ada_w"].astype(dt) + bp["ada_b"].astype(dt)
    (sh_a, sc_a, g_a, sh_m, sc_m, g_m) = jnp.split(mods, 6, axis=-1)
    h = _modulate(_ln(x), sh_a, sc_a)
    # einsum-form head-major attention + the non-causal flash kernel in
    # layout='bhsd' (r5; +3.3pt MFU over r4's exact path at batch 96).
    # The r4 flash experiment measured -1pt — but that was flash ALONE
    # with bshd relayouts; einsum-only was also ~-0.5pt. Only the
    # combination wins: projections write head-major directly and the
    # custom-call folds [B,H,N,hd] for free, so the [B,H,N,N] f32 score
    # traffic disappears without adding relayout copies. The fused qkv_w
    # keeps upstream DiT's [D, 3D] shape; its (D,3,H,hd) view means mp
    # sharding does not propagate THROUGH the reshape (leading factor 3)
    # — GSPMD inserts a reshard instead, acceptable for this domain
    # model (TP serving of DiT is not a BASELINE config).
    wqkv = bp["qkv_w"].astype(dt).reshape(D, 3, H, hd)
    bqkv = bp["qkv_b"].astype(dt).reshape(3, H, hd)
    q, k, v = [jnp.einsum("bnd,dhe->bhne", h, wqkv[:, i]) +
               bqkv[i][None, :, None, :] for i in range(3)]
    from ..kernels import flash_attention as fa
    ctx = fa.flash_attention_fwd(q, k, v, False, None, "bhsd")
    ctx = jnp.einsum("bhne,hed->bnd", ctx,
                     bp["proj_w"].astype(dt).reshape(H, hd, D))
    x = x + g_a[:, None] * (ctx + bp["proj_b"].astype(dt))
    h = _modulate(_ln(x), sh_m, sc_m)
    h = jax.nn.gelu(h @ bp["mlp_in_w"].astype(dt) +
                    bp["mlp_in_b"].astype(dt), approximate=True)
    h = h @ bp["mlp_out_w"].astype(dt) + bp["mlp_out_b"].astype(dt)
    return x + g_m[:, None] * h


def patchify(x, cfg: DiTConfig):
    """[B, C, H, W] → [B, N, p*p*C]."""
    B, C, H, W = x.shape
    p = cfg.patch_size
    x = x.reshape(B, C, H // p, p, W // p, p)
    x = x.transpose(0, 2, 4, 3, 5, 1)  # B, H/p, W/p, p, p, C
    return x.reshape(B, (H // p) * (W // p), p * p * C)


def unpatchify(x, cfg: DiTConfig):
    B, N, _ = x.shape
    p, c = cfg.patch_size, cfg.out_channels
    g = int(math.sqrt(N))
    x = x.reshape(B, g, g, p, p, c).transpose(0, 5, 1, 3, 2, 4)
    return x.reshape(B, c, g * p, g * p)


def forward(params, x, t, y, cfg: DiTConfig):
    """x: [B, C, H, W] noisy latents; t: [B] timesteps; y: [B] labels
    (num_classes = null token). → [B, out_channels, H, W]."""
    dt = cfg.dtype
    h = patchify(x.astype(dt), cfg)
    h = h @ params["patch_embed_w"].astype(dt) + \
        params["patch_embed_b"].astype(dt)
    h = h + params["pos_embed"].astype(dt)[None]
    temb = timestep_embedding(t).astype(dt)
    temb = jax.nn.silu(temb @ params["t_mlp1_w"].astype(dt) +
                       params["t_mlp1_b"].astype(dt))
    temb = temb @ params["t_mlp2_w"].astype(dt) + \
        params["t_mlp2_b"].astype(dt)
    c = jax.nn.silu(temb + params["label_embed"][y].astype(dt))

    def body(carry, bp):
        fn = _block
        if cfg.remat:
            fn = jax.checkpoint(fn, static_argnums=(3,))
        return fn(carry, c, bp, cfg), None

    h, _ = jax.lax.scan(body, h, params["blocks"])
    sh, sc = jnp.split(
        c @ params["final_ada_w"].astype(dt) +
        params["final_ada_b"].astype(dt), 2, axis=-1)
    h = _modulate(_ln(h), sh, sc)
    h = h @ params["final_w"].astype(dt) + params["final_b"].astype(dt)
    return unpatchify(h, cfg)


def diffusion_loss(params, key, x0, y, cfg: DiTConfig, n_timesteps=1000):
    """Simple DDPM epsilon-prediction MSE (the DiT training objective).
    Linear beta schedule; sigma channels (learn_sigma) are ignored in the
    loss like the reference's 'simple' loss term."""
    kb, kt, ke = jax.random.split(key, 3)
    B = x0.shape[0]
    t = jax.random.randint(kt, (B,), 0, n_timesteps)
    betas = jnp.linspace(1e-4, 0.02, n_timesteps, dtype=jnp.float32)
    alphas_bar = jnp.cumprod(1.0 - betas)
    ab = alphas_bar[t][:, None, None, None]
    eps = jax.random.normal(ke, x0.shape, jnp.float32)
    xt = jnp.sqrt(ab) * x0 + jnp.sqrt(1.0 - ab) * eps
    # classifier-free guidance dropout → null label
    drop = jax.random.bernoulli(kb, cfg.class_dropout_prob, (B,))
    y = jnp.where(drop, cfg.num_classes, y)
    pred = forward(params, xt, t, y, cfg).astype(jnp.float32)
    pred_eps = pred[:, :cfg.in_channels]
    return jnp.mean((pred_eps - eps) ** 2)


def num_params(cfg: DiTConfig) -> int:
    flat, _ = jax.tree_util.tree_flatten(
        jax.eval_shape(lambda k: init_params(k, cfg),
                       jax.ShapeDtypeStruct((2,), jnp.uint32)))
    return sum(int(math.prod(x.shape)) for x in flat)


def flops_per_image(cfg: DiTConfig) -> float:
    """Approx. train FLOPs per image (fwd+bwd = 6x fwd MACs): per patch
    token qkvo + mlp + full attention over n_patches, plus the per-block
    adaLN modulation MLP (6*D per block from the conditioning vector) and
    the patch/final projections."""
    D, T = cfg.hidden_size, cfg.n_patches
    # qkvo: 4*D^2; mlp: 2*D*(ratio*D); attention: 2*H*hd*T = 2*D*T
    per_tok = 4 * D * D + 2 * D * int(cfg.mlp_ratio * D) + 2 * D * T
    per_block = T * per_tok + D * 6 * D
    pd = cfg.patch_size ** 2 * cfg.in_channels
    patch_io = T * (pd * D + D * pd * (2 if cfg.learn_sigma else 1))
    return 6.0 * (cfg.depth * per_block + patch_io)
