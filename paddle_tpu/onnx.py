"""paddle.onnx — export stub (SURVEY.md §2.4 ONNX/program-format row:
'our ckpt: orbax; provide converter stub')."""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "paddle.onnx.export: ONNX conversion is out of scope "
        "(paddle_tpu/onnx.py). Use paddle_tpu.static.save_inference_model "
        "(a jax.export StableHLO artifact) or jit.save for serving; "
        "StableHLO→ONNX converters exist out-of-tree.")
