"""AMP — paddle.amp parity (python/paddle/amp/: auto_cast O1/O2 lists,
GradScaler with dynamic loss scaling, decorate() master weights —
upstream-canonical, unverified, SURVEY.md §0).

TPU-native stance (SURVEY.md §2.4 AMP row): bf16 is the native mixed-precision
dtype — no loss scaling needed (bf16 has fp32's exponent range), so
GradScaler degrades to a pass-through when scaling is unnecessary while
keeping the fp16 dynamic-scaling machinery for API/numeric parity.
auto_cast is implemented at the op-dispatch layer: a thread-local policy the
eager op wrapper consults to cast float inputs of whitelist ops.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes

# O1 lists — mirrors the reference's white/black list semantics: whitelist ops
# run in low precision; blacklist ops stay fp32; everything else follows its
# inputs.
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "sdpa", "flash_attention", "addmm",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "cross_entropy",
    "softmax_with_cross_entropy", "mean", "sum", "cumsum", "softmax",
    "log_softmax", "layer_norm", "batch_norm", "group_norm", "instance_norm",
    "rms_norm", "norm", "dist", "cosine_similarity", "pow", "square", "mse_loss",
    "nll_loss", "binary_cross_entropy", "bce_with_logits", "kl_div",
}

_state = threading.local()


def _amp_state():
    if not hasattr(_state, "enabled"):
        _state.enabled = False
        _state.dtype = dtypes.bfloat16
        _state.level = "O1"
        _state.custom_white = set()
        _state.custom_black = set()
    return _state


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    st = _amp_state()
    prev = (st.enabled, st.dtype, st.level, st.custom_white, st.custom_black)
    st.enabled = bool(enable)
    st.dtype = dtypes.convert_dtype(dtype)
    st.level = level
    st.custom_white = set(custom_white_list or ())
    st.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (st.enabled, st.dtype, st.level, st.custom_white, st.custom_black) = prev


amp_guard = auto_cast


def amp_dtype_for_op(op_name: str) -> Optional[np.dtype]:
    """Consulted by the eager dispatcher (ops/_registry.eager): returns the
    dtype to cast float inputs to, or None to leave them alone."""
    st = _amp_state()
    if not st.enabled:
        return None
    if st.level == "O2":
        if op_name in BLACK_LIST or op_name in st.custom_black:
            return dtypes.float32
        return st.dtype
    white = (WHITE_LIST | st.custom_white) - st.custom_black
    if op_name in white:
        return st.dtype
    if op_name in (BLACK_LIST | st.custom_black):
        return dtypes.float32
    return None


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to low precision; optimizer keeps fp32 masters
    (our Optimizer(multi_precision=True) path)."""
    d = dtypes.convert_dtype(dtype)
    model_list = models if isinstance(models, (list, tuple)) else [models]
    for m in model_list:
        if m is None:
            continue
        for _, p in m.named_parameters():
            if dtypes.is_floating_point(p.dtype):
                p._data = p._data.astype(d)
    opt_list = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
    for o in opt_list:
        if o is not None:
            o._multi_precision = True if master_weight is None else bool(master_weight)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling — needed for fp16; bf16 path is a no-op scale of
    1.0 (enable_loss_scaling=False equivalent), matching the reference's
    GradScaler API (python/paddle/amp/grad_scaler.py, unverified)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = set()  # ids of optimizers already unscaled this cycle

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """Idempotent per step-cycle — calling unscale_ then step() must not
        divide gradients by the scale twice (clip-before-step pattern)."""
        if not self._enable or id(optimizer) in self._unscaled:
            return
        self._unscaled.add(id(optimizer))
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._data * inv
                p.grad = Tensor(g, stop_gradient=True)
                if not bool(jnp.all(jnp.isfinite(g.astype(jnp.float32)))):
                    found = True
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled.discard(id(optimizer))
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)

    set_state_dict = load_state_dict


def is_bfloat16_supported(place=None):
    return True


def is_float16_supported(place=None):
    return True
