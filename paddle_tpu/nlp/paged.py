"""Paged KV-cache serving: block-table cache + ragged batch admission.

Reference analog: the fused block_multihead_attention op
(paddle.incubate.nn.functional — upstream-canonical, unverified,
SURVEY.md §0) and PaddleNLP serving's block-table KV cache, which admit
ragged request batches against one shared block pool instead of padding
every request to T_max (VERDICT r4 missing 2).

TPU-native design: everything on device is STATIC-shape —
  * the pool is one [L, N_blocks, block_size, KV, hd] tensor pair shared
    by every request; a request holds ceil(len/block_size) blocks, so
    pool memory tracks the SUM of actual lengths, not B x T_max;
  * the block table [B, M] (M = table width) and per-request lengths [B]
    are device arrays; cache reads gather pool blocks through the table,
    cache writes scatter through it (drop-mode for padded slots);
  * per-request positions ride the whole compiled path — requests at
    DIFFERENT lengths decode in one batch (the dense nlp.generation path
    requires a common position);
  * block allocation/free is host-side (BlockAllocator below) — the
    reference does the same (its block tables are built by the serving
    layer, not the kernel);
  * the indirection makes KV sharing free: with prefix caching on
    (RefcountingBlockAllocator + serving.cache.PrefixCacheIndex),
    several requests' table rows name the same pool blocks for a shared
    prompt prefix, and prefill runs only on each request's suffix.
The attention here is the exact grouped-GQA formulation (generation.
_gqa_cached_attention's paged twin) with TWO interchangeable backends:
the XLA gather path below (reference — gathers the full table width,
bit-stable, the CPU default) and the Pallas ragged paged-attention
kernel (ragged_attention.py — walks only each request's LIVE block
chain, the TPU default; `attention_impl=` selects, "auto" resolves per
backend).
"""
from __future__ import annotations

import math
import time
from collections import OrderedDict
from typing import (TYPE_CHECKING, Any, Callable, Dict, List, NamedTuple,
                    Optional, Sequence, Tuple)

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

if TYPE_CHECKING:  # annotation-only: the nlp -> serving edge stays lazy
    from ..serving.cache import PrefixCacheIndex

from ..kernels.rms_norm import rms_norm_ref
from ..kernels.rope import rope_freqs, apply_rope_half
from ..quantization import kv as kvq
from . import llama
from .generation import (_wq, _mlp_cached, _final_head_cached, _sample,
                         quantize_for_serving)
from .ragged_attention import resolve_attention_impl


class PagedKVCache(NamedTuple):
    """k/v: [L, N_blocks, block_size, KV, hd]; table: [B, M] int32 block
    ids (-1 = unassigned); lengths: [B] int32 tokens currently cached.
    k_scale/v_scale: [L, N_blocks] f32 per-(layer, block) abs-max
    dequant scales when the pool stores int8 codes (kv_dtype="int8",
    quantization.kv has the math), None for the fp pool."""
    k: jax.Array
    v: jax.Array
    table: jax.Array
    lengths: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def block_size(self) -> int:
        return self.k.shape[2]


class BlockAllocator:
    """Host-side free-list allocator over the pool's block ids.

    Mirrors the serving layer's block manager in the reference stack:
    admission takes blocks from the free list, completion returns them —
    `stats()` exposes the reuse evidence (blocks_in_use / high_water /
    reuse_count)."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))
        self._free_set: set = set(self._free)
        self._ever_used: set = set()
        self.reused_blocks = 0
        self.high_water = 0

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"pool exhausted: need {n} blocks, {len(self._free)} free")
        blocks = self._free[:n]
        del self._free[:n]
        self._free_set.difference_update(blocks)
        self._note_allocated(blocks)
        return blocks

    def _note_allocated(self, blocks: List[int]) -> None:
        self.reused_blocks += sum(1 for b in blocks if b in self._ever_used)
        self._ever_used.update(blocks)
        self.high_water = max(self.high_water,
                              self.num_blocks - self.free_blocks)

    def _check_returnable(self, b: int, seen: set, what: str) -> None:
        """A returned block id must be in range and not already free —
        a silent double free splices one block into the free list twice
        and two later requests end up writing the same KV block."""
        if not 0 <= b < self.num_blocks:
            raise ValueError(
                f"{what}: block id {b} out of range "
                f"[0, {self.num_blocks})")
        if b in self._free_set or b in seen:
            raise ValueError(
                f"{what}: block {b} is already free (double free)")

    def free(self, blocks: List[int]) -> None:
        """Return blocks to the free list. Raises ValueError on
        out-of-range or already-free ids (double-free detection) before
        mutating anything."""
        seen: set = set()
        for b in blocks:
            self._check_returnable(b, seen, "free()")
            seen.add(b)
        self._free.extend(blocks)
        self._free_set.update(blocks)

    def release(self, blocks: List[int]) -> None:
        """Alias of free() so callers can be allocator-agnostic — the
        refcounting subclass gives release() decref semantics."""
        self.free(blocks)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def stats(self) -> Dict[str, int]:
        return {
            "capacity_blocks": self.num_blocks,
            "blocks_in_use": self.num_blocks - len(self._free),
            "high_water_blocks": self.high_water,
            "reused_blocks": self.reused_blocks,
        }


class RefcountingBlockAllocator(BlockAllocator):
    """Refcounted allocator for prefix-cache block sharing.

    Three block states instead of two:

      * free        — on the free list, contents dead;
      * referenced  — refcount >= 1: held by one or more in-flight
        requests' block tables (several tables may name the same id);
      * cached      — refcount 0 but registered in the prefix index
        (`mark_cached`): contents preserved on an LRU list, reclaimable
        under pool pressure but revivable by `share()` until then.

    `allocate` prefers truly-free blocks and evicts LRU cached blocks
    only when it must (calling `on_evict(block)` so the prefix index
    unlinks them); `release` decrefs with double-free detection and
    parks cacheable blocks instead of freeing them; `share` bumps a
    live block or revives a cached one. `free_blocks` counts free AND
    cached — both are available to admission — which is exactly what
    the batcher's defer-on-no-blocks logic should see."""

    def __init__(self, num_blocks: int,
                 on_evict: Optional[Callable[[int], None]] = None):
        super().__init__(num_blocks)
        self._refs: List[int] = [0] * num_blocks
        self._cached: "OrderedDict[int, None]" = OrderedDict()  # LRU order
        self._cacheable: set = set()
        self._on_evict = on_evict
        self.evicted_blocks = 0

    def refcount(self, block: int) -> int:
        """Current refcount of `block` (0 for free AND cached blocks —
        check `is_cached` to tell them apart)."""
        return self._refs[block]

    def is_cached(self, block: int) -> bool:
        """True when `block` sits on the refcount-0 LRU cached list."""
        return block in self._cached

    @property
    def free_blocks(self) -> int:
        return len(self._free) + len(self._cached)

    def allocate(self, n: int) -> List[int]:
        if n > self.free_blocks:
            raise RuntimeError(
                f"pool exhausted: need {n} blocks, {len(self._free)} "
                f"free + {len(self._cached)} cached")
        blocks: List[int] = []
        for _ in range(n):
            if self._free:
                b = self._free.pop(0)
                self._free_set.discard(b)
            else:
                # reclaim the least-recently-parked cached block; the
                # index must forget it before its contents are reused
                b, _ = self._cached.popitem(last=False)
                self._cacheable.discard(b)
                self.evicted_blocks += 1
                if self._on_evict is not None:
                    self._on_evict(b)
            self._refs[b] = 1
            blocks.append(b)
        self._note_allocated(blocks)
        return blocks

    def share(self, blocks: List[int]) -> None:
        """Add one reference per block: bump a live block's refcount or
        revive a cached one (pulling it off the eviction list). Raises
        ValueError for a block that is neither — sharing a free block
        would hand out dead contents. Validates the WHOLE list before
        mutating anything (no half-applied bumps on error)."""
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(
                    f"share(): block id {b} out of range "
                    f"[0, {self.num_blocks})")
            if self._refs[b] <= 0 and b not in self._cached:
                raise ValueError(
                    f"share(): block {b} is neither referenced nor "
                    f"cached — its contents are gone")
        for b in blocks:
            if self._refs[b] > 0:
                self._refs[b] += 1
            else:
                del self._cached[b]
                self._refs[b] = 1

    def release(self, blocks: List[int]) -> None:
        """Drop one reference per block. At refcount 0 a block parks on
        the LRU cached list when the prefix index still names it
        (`mark_cached`), else returns to the free list. Raises
        ValueError on out-of-range ids and on releasing a block whose
        refcount is already 0 (double free) — validated over the WHOLE
        list (duplicates counted) before any refcount moves, so a
        failed call never half-applies."""
        pending: Dict[int, int] = {}
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(
                    f"release(): block id {b} out of range "
                    f"[0, {self.num_blocks})")
            pending[b] = pending.get(b, 0) + 1
            if pending[b] > self._refs[b]:
                raise ValueError(
                    f"release(): block {b} has refcount "
                    f"{self._refs[b]} (double free)")
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                if b in self._cacheable:
                    self._cached[b] = None      # newest end of the LRU
                else:
                    self._free.append(b)
                    self._free_set.add(b)

    def free(self, blocks: List[int]) -> None:
        """Refcount-aware: free() IS release() here, so allocator-
        agnostic callers (the batcher's retire path) behave correctly
        whichever allocator they hold."""
        self.release(blocks)

    def mark_cached(self, blocks: List[int]) -> None:
        """Blocks the prefix index registered: when their refcount hits
        0 they park on the cached LRU instead of the free list."""
        self._cacheable.update(blocks)

    def stats(self) -> Dict[str, int]:
        in_use = self.num_blocks - len(self._free) - len(self._cached)
        return {
            "capacity_blocks": self.num_blocks,
            "blocks_in_use": in_use,            # referenced only
            "cached_blocks": len(self._cached),  # reclaimable, not dead
            "high_water_blocks": self.high_water,
            "reused_blocks": self.reused_blocks,
            "evicted_blocks": self.evicted_blocks,
        }


def _pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << max(0, (int(n) - 1).bit_length())


class _Admission(NamedTuple):
    """One prepared-but-not-yet-activated admission: blocks are already
    allocated/shared (and the COW clone applied to the pool), the prompt's
    full blocks are registered in the prefix index so same-burst siblings
    hit, but the slot is not active until `_commit` — `_rollback` can
    still undo everything if the prefill fails."""
    slot: int
    rid: int
    toks: List[int]
    stop: int
    mn: int
    need: int
    matched: List[int]
    cached_len: int
    cow_src: Optional[int]
    fresh: List[int]
    inserted: List[int]
    chunks: List[Tuple[int, int, int]]   # (start, end, bucket) per chunk


def init_pool(cfg: llama.LlamaConfig, num_blocks: int, block_size: int,
              kv_dtype: str = "fp"):
    """Zeroed K/V pools → (k, v, k_scale, v_scale). The fp pool stores
    the compute dtype with no scales (None); kv_dtype="int8" stores
    int8 codes plus zero-initialized [L, N] per-(layer, block) abs-max
    scales — scale 0 is the never-written sentinel that dequantizes to
    the same exact zeros a fresh fp pool holds."""
    L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                 cfg.head_dim)
    if kvq.resolve_kv_dtype(kv_dtype) == "int8":
        z = jnp.zeros((L, num_blocks, block_size, KV, hd), jnp.int8)
        s = jnp.zeros((L, num_blocks), jnp.float32)
        return z, z, s, s
    z = jnp.zeros((L, num_blocks, block_size, KV, hd), cfg.dtype)
    return z, z, None, None


def build_table(allocator: BlockAllocator, lengths, max_len: int,
                block_size: int):
    """Allocate each request's blocks for up to max_len tokens → ([B, M]
    table array, per-request block lists for later free())."""
    M = -(-max_len // block_size)
    rows, owned = [], []
    for _ in lengths:
        blocks = allocator.allocate(M)
        owned.append(blocks)
        rows.append(blocks)
    return jnp.asarray(rows, jnp.int32), owned


def _write_pool(pool, table, positions, new, valid):
    """Scatter new [B, P, KV, hd] rows into pool [N, bs, KV, hd] at
    per-request absolute positions [B, P] through the block table;
    valid [B, P] masks padded slots (their writes drop)."""
    N, bs = pool.shape[0], pool.shape[1]
    B, P = positions.shape
    blk = jnp.take_along_axis(table, positions // bs, axis=1)
    flat = blk * bs + positions % bs
    flat = jnp.where(valid, flat, N * bs)          # dropped by mode="drop"
    poolf = pool.reshape(N * bs, *pool.shape[2:])
    poolf = poolf.at[flat.reshape(-1)].set(
        new.reshape(B * P, *new.shape[2:]).astype(pool.dtype), mode="drop")
    return poolf.reshape(pool.shape)


def _write_pool_int8(pool, scale, table, positions, new, valid):
    """int8 twin of `_write_pool`: quantize new [B, P, KV, hd] rows into
    the int8 pool [N, bs, KV, hd] through the block table, maintaining
    ONE per-block abs-max scale [N] (this layer's slice of the sibling
    scale pool; quantization.kv holds the math). Grow-only scale
    discipline: when this call's writes raise a block's abs-max, the
    block's EXISTING codes rescale once under the new scale — only the
    TOUCHED blocks gather/rescale/scatter (a full-pool pass would cost
    O(pool) HBM every decode step). Returns (pool', scale', dq): dq is
    the just-written rows dequantized at the committed scales, so the
    cold-prefill flash path attends over exactly what the pool now
    stores (warm reads of the same blocks see the same values —
    warm == cold by construction, not by tolerance)."""
    N, bs = pool.shape[0], pool.shape[1]
    B, P = positions.shape
    blk = jnp.take_along_axis(table, positions // bs, axis=1)   # [B, P]
    new32 = new.astype(jnp.float32)
    amax_w = jnp.where(valid, jnp.max(jnp.abs(new32), axis=(2, 3)), 0.0)
    tgt = jnp.where(valid, blk, N)            # invalid writes drop at N
    amax = jnp.zeros((N,), jnp.float32).at[tgt.reshape(-1)].max(
        amax_w.reshape(-1), mode="drop")
    scale2 = jnp.maximum(scale, kvq.scale_of(amax))
    touched = jnp.clip(blk, 0)

    def _rescale_touched(p):
        # duplicate targets all scatter the same rescaled contents
        sub = kvq.rescale_codes(p[touched],
                                scale[touched][:, :, None, None, None],
                                scale2[touched][:, :, None, None, None])
        return p.at[tgt.reshape(-1)].set(
            sub.reshape(B * P, bs, *p.shape[2:]), mode="drop")

    # rescale the touched blocks ONLY when some scale actually grew:
    # the steady-state decode step (no growth) would otherwise read and
    # rewrite every slot's full block just to store identical codes —
    # 2*block_size x the fp path's one-row write, eroding the gather-
    # bytes win. The no-growth branch is an exact no-op by the rescale
    # identity, so skipping it never changes pool contents.
    pool = lax.cond(jnp.any(scale2 > scale), _rescale_touched,
                    lambda p: p, pool)
    # quantize + scatter the new rows at the committed block scales
    s_tok = scale2[touched][:, :, None, None]                 # [B, P, 1, 1]
    codes = kvq.quantize(new32, s_tok)
    flat = jnp.where(valid, blk * bs + positions % bs, N * bs)
    poolf = pool.reshape(N * bs, *pool.shape[2:])
    poolf = poolf.at[flat.reshape(-1)].set(
        codes.reshape(B * P, *codes.shape[2:]), mode="drop")
    return poolf.reshape(pool.shape), scale2, kvq.dequantize(codes, s_tok)


def _paged_gqa_attention(q, k_pool, v_pool, table, positions, valid=None,
                         impl: str = "xla", k_scale=None, v_scale=None,
                         mesh=None, mesh_axis: str = "mp"):
    """q [B, P, H, hd] against pool blocks gathered through the table.
    positions [B, P]: query p sees pool keys at absolute positions
    j <= positions[b, p] — per-query causal, so this one path serves
    both single-token decode (P=1, position = current length) AND the
    cached-prefix suffix prefill (P>1 suffix tokens attending to the
    shared prefix blocks plus their own, never to their future).
    Cold prefill uses the in-batch flash path instead.

    impl="xla" (default) is THE reference: full-table-width gather,
    unchanged bit-for-bit from before the backend switch existed (it
    ignores `valid` — padded rows compute never-read garbage).
    impl="pallas" dispatches to the ragged Pallas kernel, which walks
    only each request's live block chain and zeroes invalid rows;
    parity is tight-tolerance, not bitwise (online softmax).

    k_scale/v_scale [N] f32 (this layer's per-block scales) mark an
    int8 pool: the XLA path dequantizes AFTER the gather (the bit-
    stable reference formulation), the Pallas kernel dequantizes inside
    its block-chunk loop with the scales riding scalar prefetch — so
    the quantized gather moves int8 bytes, not fp bytes.

    `mesh`/`mesh_axis` (pallas only) run the kernel shard_map-wrapped
    over the KV-head-sharded pool — the XLA path never needs them: its
    einsums partition under plain GSPMD."""
    if impl == "pallas":
        from .ragged_attention import ragged_paged_attention
        return ragged_paged_attention(q, k_pool, v_pool, table, positions,
                                      valid, k_scale=k_scale,
                                      v_scale=v_scale, mesh=mesh,
                                      mesh_axis=mesh_axis)
    B, P, H, hd = q.shape
    N, bs, KV, _ = k_pool.shape
    M = table.shape[1]
    tb = jnp.clip(table, 0)
    if k_scale is not None:
        # dequantize after the gather: [B, M] block scales broadcast
        # over each gathered block's [bs, KV, hd] codes (the reference
        # the in-kernel dequant is pinned against)
        k = kvq.dequantize(k_pool[tb],
                           k_scale[tb][:, :, None, None, None])
        v = kvq.dequantize(v_pool[tb],
                           v_scale[tb][:, :, None, None, None])
        k = k.reshape(B, M * bs, KV, hd)
        v = v.reshape(B, M * bs, KV, hd)
    else:
        k = k_pool[tb].reshape(B, M * bs, KV, hd)
        v = v_pool[tb].reshape(B, M * bs, KV, hd)
    rep = H // KV
    qg = q.reshape(B, P, KV, rep, hd)
    s = jnp.einsum("bpkrd,btkd->bkrpt", qg, k,
                   preferred_element_type=jnp.float32) / math.sqrt(hd)
    # [B, P, T] key-visibility per query → broadcast over (KV, rep)
    vis = (jnp.arange(M * bs)[None, None, :] <= positions[:, :, None]
           )[:, None, None, :, :]
    s = jnp.where(vis, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkrpt,btkd->bpkrd", p, v,
                   preferred_element_type=jnp.float32)
    return o.reshape(B, P, H, hd).astype(q.dtype)


def _spec_gqa_attention(q, pk, pv, table, base_len, sk, sv, vis,
                        k_scale=None, v_scale=None, impl: str = "xla",
                        mesh=None, mesh_axis: str = "mp"):
    """The speculative score path's attention: q [B, P, H, hd] over the
    committed pool history PLUS an in-register draft/verify suffix
    slab. The pool is READ-ONLY here — visibility for pool keys is
    j < base_len[b] (the committed length; nothing speculative has
    been written), and suffix slab row s (this step's tokens plus
    previously drafted ones, sk/sv [B, S, KV, hd]) is visible to query
    p iff vis[p, s] — the chain's causal triangle, or the packed
    tree's ancestor-or-self mask (each node sees exactly its
    root-to-node path). Together a query at committed position
    base_len + r along its path sees exactly the base_len + r + 1 keys
    plain write-then-gather decode would — same key set and values
    (slab rows pass through the pool dtype), softmax over the
    concatenated score axis.

    k_scale/v_scale mark an int8 pool: dequantized after the gather
    (the XLA reference formulation). Slab rows stay full precision —
    the committed codes a LATER step reads go through the normal
    quantize-on-commit path, so spec-vs-plain parity under int8 KV is
    a documented match-rate floor, not bitwise (README
    "Speculative decoding").

    impl="pallas" routes the whole thing through the ragged Pallas
    kernel's suffix-slab operand (nlp/ragged_attention.py): the pool
    sweep stays the int8-gathered block-chunk loop and the slab folds
    into the same online softmax at the grid's extra chunk — instead
    of this XLA concat formulation, which stays the bit-stable parity
    reference (and the CPU default). `mesh`/`mesh_axis` (pallas only)
    shard that kernel call on heads — the slab and its accept walk
    shard naturally, since slab rows carry whole KV heads."""
    B, P, H, hd = q.shape
    N, bs, KV, _ = pk.shape
    M = table.shape[1]
    S = sk.shape[1]
    if impl == "pallas":
        from .ragged_attention import ragged_paged_attention
        # pool visibility j < base_len == positions j <= base_len - 1,
        # every query valid (inactive slots score garbage the caller
        # discards — same as the XLA formulation below)
        return ragged_paged_attention(
            q, pk, pv, table,
            jnp.broadcast_to((base_len - 1)[:, None], (B, P)),
            jnp.ones((B, P), bool), k_scale=k_scale, v_scale=v_scale,
            suffix_k=sk, suffix_v=sv,
            suffix_vis=jnp.broadcast_to(vis[None], (B, P, S)),
            mesh=mesh, mesh_axis=mesh_axis)
    tb = jnp.clip(table, 0)
    if k_scale is not None:
        k = kvq.dequantize(pk[tb],
                           k_scale[tb][:, :, None, None, None])
        v = kvq.dequantize(pv[tb],
                           v_scale[tb][:, :, None, None, None])
        k = k.reshape(B, M * bs, KV, hd)
        v = v.reshape(B, M * bs, KV, hd)
    else:
        k = pk[tb].reshape(B, M * bs, KV, hd)
        v = pv[tb].reshape(B, M * bs, KV, hd)
    rep = H // KV
    qg = q.reshape(B, P, KV, rep, hd)
    sp = jnp.einsum("bpkrd,btkd->bkrpt", qg, k,
                    preferred_element_type=jnp.float32) / math.sqrt(hd)
    vis_p = (jnp.arange(M * bs)[None, :] < base_len[:, None]
             )[:, None, None, None, :]
    sp = jnp.where(vis_p, sp, -1e30)
    ss = jnp.einsum("bpkrd,bskd->bkrps", qg, sk.astype(q.dtype),
                    preferred_element_type=jnp.float32) / math.sqrt(hd)
    ss = jnp.where(vis[None, None, None, :, :], ss, -1e30)
    p = jax.nn.softmax(jnp.concatenate([sp, ss], axis=-1), axis=-1)
    o = jnp.einsum("bkrpt,btkd->bpkrd", p[..., :M * bs], v,
                   preferred_element_type=jnp.float32) \
        + jnp.einsum("bkrps,bskd->bpkrd", p[..., M * bs:],
                     sv.astype(q.dtype),
                     preferred_element_type=jnp.float32)
    return o.reshape(B, P, H, hd).astype(q.dtype)


def _forward_spec(params, layers, tokens, cache, positions, base_len,
                  slab_k, slab_v, row0, cfg, vis=None,
                  impl: str = "xla", mesh=None, mesh_axis: str = "mp"):
    """The speculative score-path forward: tokens [B, P] at per-request
    absolute positions, attending to the committed pool (READ-ONLY,
    visibility < base_len) plus the spec slab (previously drafted rows
    and this call's own). The new tokens' per-layer K/V land in slab
    rows [row0, row0 + P) — NEVER the pool: verify-then-commit writes
    only accepted rows afterwards, so a rejected draft token cannot
    poison the pool, the prefix cache, or an int8 block's grow-only
    scale. `layers` may be a truncated stack (the draft's) — the
    slab's leading dim matches it; embed/norm/head come from the full
    `params` either way (the self-speculative trick: the target's pool
    layers 0..d-1 ARE the d-layer draft's cache — and when the batcher
    built a draft-from-w8 stack, `layers` is that int8 tree while
    `params` stays the target's). `vis` [P, S] gives each query its
    visible slab rows (None = the chain causal triangle relative to
    row0 — the pre-tree behavior); `impl` picks the score-path
    attention backend ("xla" concat reference | "pallas" suffix-slab
    kernel), with `mesh`/`mesh_axis` shard_map-wrapping the pallas
    case on the TP mesh. Returns (logits [B, P, V], slab_k', slab_v')."""
    cd = cfg.dtype
    T_rope = cache.table.shape[1] * cache.k.shape[2]
    x = jnp.take(params["embed_tokens"], tokens, axis=0).astype(cd)
    cos, sin = rope_freqs(cfg.head_dim, T_rope, cfg.rope_theta,
                          jnp.float32)
    B, P = tokens.shape
    H, KV, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    if vis is None:
        # chain slab visibility: query p (slab row row0 + p) sees slab
        # rows <= its own — the causal triangle the tree's ancestor
        # mask degenerates to at branching [1, 1, ...]
        vis = jnp.arange(slab_k.shape[2])[None, :] \
            <= (row0 + jnp.arange(P))[:, None]

    def body(carry, lp):
        x, sk_all, sv_all, li = carry
        pk = lax.dynamic_slice_in_dim(cache.k, li, 1, 0)[0]
        pv = lax.dynamic_slice_in_dim(cache.v, li, 1, 0)[0]
        ks = None if cache.k_scale is None else \
            lax.dynamic_slice_in_dim(cache.k_scale, li, 1, 0)[0]
        vs = None if cache.v_scale is None else \
            lax.dynamic_slice_in_dim(cache.v_scale, li, 1, 0)[0]
        sk = lax.dynamic_slice_in_dim(sk_all, li, 1, 0)[0]
        sv = lax.dynamic_slice_in_dim(sv_all, li, 1, 0)[0]
        h = rms_norm_ref(x, lp["input_layernorm"], cfg.rms_norm_eps)
        q = (h @ _wq(lp, "q_proj", cd)).reshape(B, P, H, hd)
        k = (h @ _wq(lp, "k_proj", cd)).reshape(B, P, KV, hd)
        v = (h @ _wq(lp, "v_proj", cd)).reshape(B, P, KV, hd)
        q, k = apply_rope_half(q, k, cos, sin, positions)
        # slab rows pass through the slab (== pool compute) dtype so
        # spec attention sees the same roundtrip a pool write-then-
        # gather would give plain decode
        sk = lax.dynamic_update_slice_in_dim(sk, k.astype(sk.dtype),
                                             row0, axis=1)
        sv = lax.dynamic_update_slice_in_dim(sv, v.astype(sv.dtype),
                                             row0, axis=1)
        a = _spec_gqa_attention(q, pk, pv, cache.table, base_len,
                                sk, sv, vis, ks, vs, impl=impl,
                                mesh=mesh, mesh_axis=mesh_axis)
        a = a.reshape(B, P, H * hd) @ _wq(lp, "o_proj", cd)
        sk_all = lax.dynamic_update_slice_in_dim(sk_all, sk[None], li, 0)
        sv_all = lax.dynamic_update_slice_in_dim(sv_all, sv[None], li, 0)
        x = x + a
        h = rms_norm_ref(x, lp["post_attention_layernorm"],
                         cfg.rms_norm_eps)
        x = x + _mlp_cached(h, lp, cfg)
        return (x, sk_all, sv_all, li + 1), None

    (x, slab_k, slab_v, _), _ = lax.scan(
        body, (x, slab_k, slab_v, jnp.int32(0)), layers)
    logits = _final_head_cached(params, x, cfg)
    return logits, slab_k, slab_v


def _attention_paged(x, lp, cfg, cos, sin, pk, pv, table, positions,
                     valid, is_prefill, attention_impl: str = "xla",
                     pks=None, pvs=None, mesh=None,
                     mesh_axis: str = "mp"):
    """One layer's attention. positions [B, P] per-request absolute
    positions of x's tokens; valid masks padded slots. Returns
    (out, pk', pv', pks', pvs') with the new tokens written into the
    pool — quantized on the commit write when pks/pvs carry this
    layer's int8 block scales (None = fp pool, the unchanged path)."""
    B, P, D = x.shape
    H, KV, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    cd = cfg.dtype
    q = (x @ _wq(lp, "q_proj", cd)).reshape(B, P, H, hd)
    k = (x @ _wq(lp, "k_proj", cd)).reshape(B, P, KV, hd)
    v = (x @ _wq(lp, "v_proj", cd)).reshape(B, P, KV, hd)
    q, k = apply_rope_half(q, k, cos, sin, positions)
    if pks is None:
        pk = _write_pool(pk, table, positions, k, valid)
        pv = _write_pool(pv, table, positions, v, valid)
        kq, vq = k, v
    else:
        pk, pks, kq = _write_pool_int8(pk, pks, table, positions, k, valid)
        pv, pvs, vq = _write_pool_int8(pv, pvs, table, positions, v, valid)
        # every consumer sees the quantize→dequantize roundtrip of this
        # call's own writes — a later cached-prefix read of the same
        # blocks sees the same KV values (warm == cold by construction)
        kq, vq = kq.astype(cd), vq.astype(cd)
    if is_prefill:
        # the prompt attends only to itself: plain causal self-attention
        # over the right-padded batch (rows past each request's length
        # produce garbage that is never read — their pool writes are
        # dropped and their logits never selected)
        from ..kernels import flash_attention as fa
        o = fa._flash_impl(q, kq, vq, True, None)
    else:
        # decode AND cached-prefix suffix prefill: gather through the
        # table with per-query causal visibility (j <= position)
        o = _paged_gqa_attention(q, pk, pv, table, positions, valid,
                                 impl=attention_impl, k_scale=pks,
                                 v_scale=pvs, mesh=mesh,
                                 mesh_axis=mesh_axis)
    return (o.reshape(B, P, H * hd) @ _wq(lp, "o_proj", cd)), pk, pv, \
        pks, pvs


def forward_paged(params, tokens, cache: PagedKVCache, positions, valid,
                  cfg, is_prefill: bool, attention_impl: str = "xla",
                  mesh=None, mesh_axis: str = "mp"):
    """tokens [B, P] at per-request absolute `positions` [B, P] →
    (logits [B, P, V] f32, cache'). visible_len for decode = position+1
    (the just-written token included). `attention_impl` selects the
    paged-attention backend ("xla" reference gather | "pallas" ragged
    kernel) for the non-prefill path; cold prefill keeps flash.
    `mesh`/`mesh_axis` shard_map-wrap the pallas kernel on the TP mesh
    (no-op for "xla", which shards under plain GSPMD)."""
    cd = cfg.dtype
    # rope spans the per-request table width (max reachable position),
    # NOT the whole pool — the pool is ~B x larger by construction
    T_rope = cache.table.shape[1] * cache.k.shape[2]
    x = jnp.take(params["embed_tokens"], tokens, axis=0).astype(cd)
    cos, sin = rope_freqs(cfg.head_dim, T_rope, cfg.rope_theta, jnp.float32)
    visible_len = positions[:, -1] + 1

    def body(carry, lp):
        # ks_all/vs_all are the [L, N] scale pools in int8-KV mode and
        # None for fp — the None branch traces to the exact pre-
        # quantization jaxpr (None adds no carry leaves), keeping the
        # fp path byte-identical with quantization off
        x, pk_all, pv_all, ks_all, vs_all, li = carry
        pk = lax.dynamic_slice_in_dim(pk_all, li, 1, 0)[0]
        pv = lax.dynamic_slice_in_dim(pv_all, li, 1, 0)[0]
        ks = None if ks_all is None else \
            lax.dynamic_slice_in_dim(ks_all, li, 1, 0)[0]
        vs = None if vs_all is None else \
            lax.dynamic_slice_in_dim(vs_all, li, 1, 0)[0]
        h = rms_norm_ref(x, lp["input_layernorm"], cfg.rms_norm_eps)
        a, pk, pv, ks, vs = _attention_paged(
            h, lp, cfg, cos, sin, pk, pv, cache.table, positions, valid,
            is_prefill, attention_impl, ks, vs, mesh=mesh,
            mesh_axis=mesh_axis)
        pk_all = lax.dynamic_update_slice_in_dim(pk_all, pk[None], li, 0)
        pv_all = lax.dynamic_update_slice_in_dim(pv_all, pv[None], li, 0)
        if ks_all is not None:
            ks_all = lax.dynamic_update_slice_in_dim(ks_all, ks[None],
                                                     li, 0)
            vs_all = lax.dynamic_update_slice_in_dim(vs_all, vs[None],
                                                     li, 0)
        x = x + a
        h = rms_norm_ref(x, lp["post_attention_layernorm"],
                         cfg.rms_norm_eps)
        x = x + _mlp_cached(h, lp, cfg)
        return (x, pk_all, pv_all, ks_all, vs_all, li + 1), None

    (x, pk, pv, ks, vs, _), _ = lax.scan(
        body, (x, cache.k, cache.v, cache.k_scale, cache.v_scale,
               jnp.int32(0)), params["layers"])
    logits = _final_head_cached(params, x, cfg)
    new_len = jnp.maximum(cache.lengths, visible_len)
    return logits, PagedKVCache(pk, pv, cache.table, new_len, ks, vs)


def paged_generate(params, tokens, lengths, cfg: llama.LlamaConfig,
                   max_new_tokens: int = 32, block_size: int = 64,
                   allocator: Optional[BlockAllocator] = None,
                   num_blocks: Optional[int] = None,
                   temperature: float = 1.0, top_k: int = 0,
                   top_p: float = 1.0, greedy: bool = True,
                   pad_token_id: int = 0,
                   key: Optional[jax.Array] = None,
                   attention_impl: str = "auto"):
    """Ragged batched generation over one shared block pool.

    tokens [B, P_max] right-padded prompts; lengths [B] real prompt
    lengths (REQUESTS MAY DIFFER — the dense generate() cannot).
    Returns (ids [B, max_new_tokens], allocator, owned) — `owned` is the
    per-request block lists; free them back to the allocator when each
    request completes so later admissions reuse the pool.
    `attention_impl` picks the decode attention backend ("xla"
    reference | "pallas" ragged kernel | "auto" per backend).
    """
    attention_impl = resolve_attention_impl(attention_impl)
    B, P = tokens.shape
    lengths_np = np.asarray(lengths)
    max_total = int(lengths_np.max()) + max_new_tokens
    if allocator is None:
        n = num_blocks or (B * -(-max_total // block_size))
        allocator = BlockAllocator(n)
    table, owned = build_table(allocator, lengths_np, max_total, block_size)
    kp, vp, ksc, vsc = init_pool(cfg, allocator.num_blocks, block_size)
    cache = PagedKVCache(kp, vp, table,
                         jnp.zeros((B,), jnp.int32), ksc, vsc)
    if key is None:
        key = jax.random.PRNGKey(0)
    lengths = jnp.asarray(lengths, jnp.int32)

    # prefill at per-request positions; padded rows write nothing
    positions = jnp.broadcast_to(jnp.arange(P)[None], (B, P))
    valid = positions < lengths[:, None]
    logits, cache = forward_paged(params, tokens, cache, positions, valid,
                                  cfg, is_prefill=True)
    # ragged last-token logits: position lengths[b] - 1 per request
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    key, sub = jax.random.split(key)
    first = _sample(last, sub, temperature, top_k, top_p, greedy)
    # the prefill wrote only the prompt; fix lengths to the real ones
    cache = cache._replace(lengths=lengths)

    def step(carry, _):
        tok, cache, key = carry
        pos = cache.lengths[:, None]                       # [B, 1]
        logits, cache = forward_paged(
            params, tok[:, None], cache, pos,
            jnp.ones_like(pos, bool), cfg, is_prefill=False,
            attention_impl=attention_impl)
        key, sub = jax.random.split(key)
        nxt = _sample(logits[:, 0], sub, temperature, top_k, top_p, greedy)
        return (nxt, cache, key), nxt

    (last_tok, cache, _), rest = lax.scan(
        step, (first, cache, key), None, length=max_new_tokens - 1)
    out = jnp.concatenate([first[:, None], rest.T.astype(jnp.int32)],
                          axis=1)
    return out, allocator, owned


class ContinuousBatcher:
    """Continuous batching over the shared block pool (reference analog:
    PaddleNLP serving's in-flight batching over the block cache — pulled
    forward from the VERDICT r4 next-8 'r6 follow-up').

    Host-side scheduler over compiled device steps: a fixed set of B
    batch slots decodes in lock-step chunks; when a request finishes
    (eos or budget) its blocks return to the allocator and queued
    requests are admitted into the free slots by a bucketed prefill —
    decode of the other slots never re-pads or re-compiles (shapes are
    static: the chunk step compiles once per (B, M)).

    Prefill is bucketed, chunked, and batched: the suffix pads to a
    power-of-two bucket ladder (masked through valid/positions), longer
    suffixes split into sequential largest-bucket chunks through the
    per-query-causal paged path, and same-bucket admissions in one burst
    prefill in a single compiled call. Every shape comes from a finite
    (group, bucket, phase) set memoized in `_prefill_exe`, so
    steady-state admission NEVER recompiles (`prefill_compile_count`
    goes flat after `warmup_prefill()`); `prefill_pad_tokens` counts the
    padding overhead bucketing trades for that.

    Prefill is FUSED with decode (`fused_prefill=True`): when an
    admission lands while slots are decoding, one compiled call carries
    `max_batch` decode rows PLUS up to one bucket-sized chunk of prefill
    rows — the Ragged Paged Attention mixed-mode batch — so in-flight
    decoding advances by its chunk in the same device program that
    prefills the admission, instead of stalling while a standalone
    prefill monopolizes the device. Prepared admissions wait in a
    pending pipeline; `step()` decides each tick whether to piggyback
    the next prefill unit on the decode chunk (fused), run it standalone
    (nothing decoding — nothing to stall), or decode only. Chunked long
    prompts stream ONE fused chunk per step. `fused_steps` counts
    piggybacked calls, `decode_stall_steps` counts standalone prefill
    calls that ran while slots were decoding (the unfused cost), and
    fused shapes are memoized/AOT-warmed exactly like standalone ones.
    A fused step carries up to `fused_units` CONSECUTIVE pending units
    when they share this step's chunk bucket and no cross-unit block
    dependency forces ordering — admission bursts and co-pending
    chunked long prompts drain up to `fused_units` x faster under
    sustained decode load, with shapes still drawn from the finite
    warmed ladder (total prefill rows = units x group pad).

    Attention backend (`attention_impl=`): "xla" is the reference
    full-table-width gather (bit-stable, the CPU default); "pallas" is
    the ragged paged-attention kernel (ragged_attention.py) that walks
    only each request's LIVE block chain (the TPU default — decode HBM
    traffic tracks live pool bytes, not table width); "auto" resolves
    per backend at construction. Every compiled-shape memo keys on the
    resolved impl.

    Quantized serving (`weight_dtype=`, `kv_dtype=`): "int8" weights
    route params through generation.quantize_for_serving (int8 codes +
    per-output-channel scales, dequantized in-register at the consuming
    dot — the path bench.py's w8 decode numbers measure); "int8" KV
    stores the block pools as int8 codes with per-(layer, block)
    abs-max scales in a sibling scale pool (quantization.kv is the
    single-source math), quantized on every prefill/decode commit
    write, dequantized after the gather on the XLA path and inside the
    kernel's block-chunk loop on the Pallas path — per-request decode
    HBM traffic drops to ~half of fp block bytes (kv_bytes_per_token()
    quantifies it, scale overhead included). Defaults ("fp") keep both
    paths byte-identical to the pre-quantization behavior; every
    compiled-shape memo keys on (weight_dtype, kv_dtype) next to the
    attention impl.

    Self-speculative decoding (`speculative=`, `spec_k=`,
    `draft_layers=`): decode is memory-bound — every plain step sweeps
    the weights + live KV to emit ONE token per slot. With spec on, a
    cheap draft (the SAME model truncated to `draft_layers`; the
    committed pool's layers 0..d-1 ARE its KV cache, so no second
    weight set or pool exists) proposes `spec_k` tokens, and the
    target scores all k+1 positions in ONE call — the per-query
    causal mask is exactly the multi-token-suffix primitive — then
    accepts the longest prefix matching its own greedy tokens plus
    one corrected token. Verify-then-commit: scoring never writes the
    pool (proposal K/V ride an in-register slab); only accepted rows
    commit, row-sequentially, so rejection never poisons the pool /
    prefix cache / int8 scales and greedy output is identical to
    plain decode by construction. Admission pressure keeps using the
    fused plain-decode tick; `submit(speculative=False)` opts one
    request out (the engine quarantine's fallback); the spec config
    rides every memo/warmup key and `warmup_prefill` compiles the
    draft/verify pair. `spec_stats()` reports acceptance accounting.

    Observability (`trace=`, `flight_recorder_cap=`): an optional
    `serving.trace.TraceSink` collects per-request timelines (prepared
    / prefill_chunk / retired events carrying bucket, pad,
    cached-token and fused-vs-standalone annotations, keyed by rid);
    the always-on `flight` FlightRecorder keeps a bounded ring of one
    record per step tick — mode chosen, unit composition, bucket /
    group pad, free slots / blocks, compile-memo hit or miss —
    written BEFORE the device call so a failing step is the ring's
    last record. Both are host-side bookkeeping only: no device
    syncs, and the compiled-shape memo keys never see them.

    Tensor-parallel serving (`mesh=`): a serving.tp.MeshConfig shards
    the weights (every projection output-split — never a contracted
    dim, so sharded matmuls keep the unsharded summation order), the
    paged KV pool (head axis) and the w8 scale leaves across a 1-D
    device mesh; GSPMD partitions the same compiled step programs
    from sharded avals, the host-side scheduler is untouched, and
    greedy output is BIT-identical to the single-device batcher. The
    mesh key rides every compiled-shape memo key after the qkey
    (() when off — keys stay byte-identical). export_kv gathers the
    sharded pool to full host blocks and import_kv's scatter
    preserves the pool sharding, so KV migration (and disaggregated
    prefill→decode handoff) works across replicas of DIFFERENT mesh
    shapes — snapshots are mesh-agnostic by construction.

    Usage:
        cb = ContinuousBatcher(params, cfg, max_batch=2, block_size=16,
                               max_total_len=256, max_new_tokens=16)
        rid = cb.submit([tok, tok, ...])
        cb.run()              # drain queue + in-flight
        out = cb.outputs[rid] # list of generated ids
    """

    def __init__(self, params, cfg, max_batch: int, block_size: int,
                 max_total_len: int, max_new_tokens: int,
                 eos_token_id: Optional[int] = None,
                 num_blocks: Optional[int] = None, chunk: int = 8,
                 prefix_cache: bool = False,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 max_prefill_bucket: int = 512,
                 fused_prefill: bool = True, fused_units: int = 1,
                 attention_impl: str = "auto",
                 weight_dtype: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 speculative: bool = False, spec_k: int = 4,
                 draft_layers: Optional[int] = None,
                 spec_tree: Optional[Sequence[int]] = None,
                 spec_draft_w8: bool = False,
                 spec_attention_impl: Optional[str] = None,
                 trace=None, flight_recorder_cap: int = 64,
                 profile_sample_every: int = 64,
                 fault_injector=None, replica_id: str = "r0",
                 mesh=None):
        # multi-replica attribution: stamped on every `prepared` trace
        # event so a Router's merged trace artifact (and
        # tools/trace_report.py's per-replica grouping) can tell which
        # replica's batcher admitted each request
        self.replica_id = str(replica_id)
        # quantized serving (ROADMAP direction 4): weight_dtype="int8"
        # routes params through generation.quantize_for_serving (the
        # same int8 weight-only path bench.py measures — idempotent on
        # already-quantized trees, so a caller that pre-quantized for
        # mesh placement via generation.quantized_specs, the way
        # inference/llm.py does, passes through); kv_dtype="int8"
        # stores the K/V
        # pools as int8 codes with per-(layer, block) abs-max scales in
        # a sibling scale pool (quantization.kv holds the single-source
        # math), quantized on every prefill/decode commit write and
        # dequantized after the gather (xla) or inside the kernel's
        # block-chunk loop (pallas). Defaults keep the fp path
        # byte-identical to the pre-quantization behavior.
        self.weight_dtype = "fp" if weight_dtype in (None, "fp") \
            else weight_dtype
        if self.weight_dtype not in ("fp", "int8"):
            raise ValueError(
                f"weight_dtype must be 'fp'/'int8' (or None), "
                f"got {weight_dtype!r}")
        if self.weight_dtype == "int8" and not any(
                k.endswith(":scale") for k in params["layers"]):
            params = quantize_for_serving(params, bits=8)
        self.kv_dtype = kvq.resolve_kv_dtype(kv_dtype)
        # ptlint: memo-invariant(weights and model config never change for a live batcher)
        self.params, self.cfg = params, cfg
        # chaos harness: an optional serving.faults.FaultInjector
        # consulted at every device-call boundary (_gate) — fail /
        # hang / pass, deterministically. None in production. The
        # attach notification lets an injector that follows a replica
        # slot across supervisor respawns re-arm per-incarnation rules
        # (hasattr-guarded: any object with a check() works here).
        self._fault = fault_injector
        if fault_injector is not None and hasattr(fault_injector,
                                                  "attach"):
            fault_injector.attach(replica_id)
        # ptlint: memo-invariant(pool geometry is fixed at construction)
        self.B, self.bs = max_batch, block_size
        # resolved once: every traced fn closes over the concrete
        # backend and every compiled-shape memo keys on it — and on the
        # resolved (weight_dtype, kv_dtype) pair, so the warmup ladder
        # a quantized batcher compiles can never be confused with an fp
        # one's (the zero-post-warmup-recompiles gate covers both)
        # ptlint: trace-config
        self.attention_impl = resolve_attention_impl(attention_impl)
        # ptlint: trace-config
        self._qkey = (self.weight_dtype, self.kv_dtype)
        # tensor-parallel serving (ROADMAP direction 1): `mesh` is a
        # serving.tp.MeshConfig — projections output-split (never a
        # contracted dim: bit-identical greedy decode, see tp.py),
        # the paged KV pool sharded on its head axis, scheduler
        # state replicated; GSPMD partitions the SAME step programs
        # from sharded avals, so the host-side scheduler and the AOT
        # warmup ladder are untouched. Every compiled-shape memo key
        # carries the mesh key AFTER the qkey (() when mesh is off —
        # a single-device batcher's keys are byte-identical to a
        # pre-mesh build's, the _skey convention).
        # ptlint: trace-config
        self._mkey = () if mesh is None else mesh.key()
        # ptlint: memo-invariant(fixed at construction; its key() IS _mkey, which rides every memo key)
        self._mesh_cfg = mesh
        # ptlint: memo-invariant(built once from _mesh_cfg — mesh identity rides every memo key via _mkey)
        self._mesh = None
        self._shard_params = None
        self._shard_pool = None
        self._shard_repl = None
        if mesh is not None:
            # attention_impl="pallas" composes: the step programs call
            # the ragged kernel shard_map-wrapped over the head-sharded
            # pool (ragged_attention._shard_specs), so each device runs
            # the per-device Pallas program on its head shard and GSPMD
            # stitches the head axis — no XLA-gather fallback under TP
            from ..serving.tp import build_shardings
            (self._mesh, self._shard_params, self._shard_pool,
             self._shard_repl) = build_shardings(mesh, cfg, self.params)
            self.params = jax.device_put(self.params, self._shard_params)
        # self-speculative decoding (ROADMAP direction 5(b)): a cheap
        # draft — the SAME model truncated to `draft_layers` (None =
        # full depth) — proposes spec_k tokens autoregressively off
        # the committed pool (layer l's KV depends only on layers < l,
        # so the target's pool layers 0..d-1 ARE the d-layer draft's
        # cache: no second weight set, no second pool); the target
        # then scores all k+1 positions in ONE call and accepts the
        # longest greedy-matching prefix plus one corrected token.
        # Verify-then-commit: scoring never writes the pool — accepted
        # rows commit afterwards, row-sequentially, so rejection never
        # poisons the pool / prefix cache / int8 scales and greedy
        # output is identical to plain decode by construction.
        # serving.speculative holds the config/stat types (lazy import
        # below, like trace/profiling — dependency-free module).
        # Speculation v2 widens the draft to a token TREE
        # (spec_tree=[b0, b1, ...]: b0 candidates for the next token,
        # b1 children each, ... — spec_k is then DERIVED as the node
        # count), optionally reads the draft sweep's weights from an
        # int8 quantization of the truncated stack (spec_draft_w8 —
        # draft bytes halve, verification still runs the target's own
        # weights so tokens are unchanged), and can route the verify's
        # score path through the ragged kernel's suffix-slab operand
        # (spec_attention_impl="pallas"; None inherits the batcher's
        # resolved backend, so CPU stays on the XLA concat reference).
        from ..serving.speculative import SpecConfig, SpecStats
        self.speculative = bool(speculative)
        # ptlint: memo-invariant(frozen at construction; its key() rides _skey)
        self._spec_cfg = SpecConfig(spec_k, draft_layers,
                                    num_layers=cfg.num_hidden_layers,
                                    tree=spec_tree,
                                    draft_w8=spec_draft_w8)
        self.spec_k = self._spec_cfg.k
        self.spec_tree = self._spec_cfg.tree
        self._draft_depth = self._spec_cfg.depth(cfg.num_hidden_layers)
        # ptlint: memo-invariant(resolved once at construction; rides _skey)
        self.spec_attention_impl = self.attention_impl \
            if spec_attention_impl is None \
            else resolve_attention_impl(spec_attention_impl)
        # draft-from-w8: quantize the truncated layer stack ONCE at
        # construction (int8 codes + per-channel scales — the same
        # weight-only math weight_dtype="int8" serves) so every draft
        # sweep streams int8 weight bytes. Only built when the target
        # itself serves fp weights: an int8 target's layers already
        # ARE the quantized tree and slicing them is free.
        self._spec_dlayers = None
        if self.speculative and self._spec_cfg.draft_w8 \
                and self.weight_dtype == "fp":
            trunc = jax.tree_util.tree_map(
                lambda x: x[:self._draft_depth], params["layers"])
            self._spec_dlayers = quantize_for_serving(
                {"layers": trunc}, bits=8)["layers"]
        # every compiled-shape memo key carries the spec config BEFORE
        # the trailing qkey (() when spec is off — plain batchers' keys
        # are byte-identical to before), so a spec batcher's warmed
        # ladder can never be confused with a plain one's
        # ptlint: trace-config
        self._skey = ((self._spec_cfg.key(cfg.num_hidden_layers)
                       + (self.spec_attention_impl,))
                      if self.speculative else ())
        self.spec = SpecStats()
        self._spec_cache: Dict[Tuple, Any] = {}
        self._spec_draft_fn = None
        self._spec_verify_fn = None
        # per-request spec opt-out (engine quarantine's plain-decode
        # fallback for victims of a failed spec tick) + the [B] device
        # mirror of per-slot participation, invalidated on admit/retire
        self._no_spec: set = set()
        self._spec_ok_dev = None
        self.max_total = max_total_len
        # ptlint: memo-invariant(pool geometry is fixed at construction)
        self.M = -(-max_total_len // block_size)
        self.max_new = max_new_tokens
        # ptlint: memo-invariant(eos id is fixed at construction)
        self.eos = eos_token_id
        # ptlint: memo-invariant(decode chunk length is fixed at construction)
        self.chunk = chunk
        # prefill bucket ladder: suffixes pad to the smallest bucket that
        # fits and longer ones split into largest-bucket chunks, so every
        # admission hits one of a FIXED set of compiled shapes instead of
        # tracing per prompt length. None = auto power-of-two ladder
        # (8, 16, ... capped by max_prefill_bucket and the table span);
        # an empty sequence disables bucketing (exact shapes — one
        # compile per distinct suffix length, the pre-bucketing behavior)
        if prefill_buckets is None:
            # the top bucket never exceeds the table span — no suffix
            # can be longer than max_total_len, so a bigger bucket would
            # only buy pad tokens (the cap itself may be non-pow2)
            cap = max(1, min(int(max_total_len), int(max_prefill_bucket)))
            ladder, b = [], 8
            while b < cap:
                ladder.append(b)
                b *= 2
            ladder.append(cap)
            self._buckets: Tuple[int, ...] = tuple(sorted(set(ladder)))
        else:
            self._buckets = tuple(sorted({int(x) for x in prefill_buckets}))
            if any(x < 1 for x in self._buckets):
                raise ValueError("prefill_buckets must be positive")
        self._prefill_fns: Dict[bool, Any] = {}     # cold -> jitted fn
        self._prefill_cache: Dict[Tuple[int, int, bool, str], Any] = {}
        self.prefill_pad_tokens = 0
        # fused prefill+decode: admissions landing mid-decode piggyback
        # up to `fused_units` prefill units on the decode chunk call
        # instead of stalling every in-flight slot behind a standalone
        # prefill
        self._fused = bool(fused_prefill)
        if int(fused_units) < 1:
            raise ValueError("fused_units must be >= 1")
        self.fused_units = int(fused_units)
        self._fused_fn = None
        self._fused_cache: Dict[Tuple[int, int, str], Any] = {}
        # the plain decode chunk, AOT-compiled like the prefill shapes
        # (warmup covers it, so a decode-only stretch after a fused
        # stretch never pays a first-call compile)
        self._chunk_cache: Dict[Tuple[int, str], Any] = {}
        # prepared-but-not-fully-prefilled admissions: [record, chunks
        # done] — the record's slot and blocks are reserved for the
        # whole mid-stream prefill (free_slots counts them taken)
        self._pending: List[List] = []
        self.fused_steps = 0          # piggybacked prefill calls
        self.fused_unit_count = 0     # prefill units those calls carried
        self.decode_stall_steps = 0   # standalone prefills that stalled
        # observed real chunk lengths (len -> count): the data a
        # workload-specific bucket ladder is fitted from (bucket_tuner)
        self.prefill_suffix_hist: Dict[int, int] = {}
        # KV-transfer accounting (serving/kvtransfer.py): snapshots
        # exported/imported through this batcher plus a host count of
        # prefill rows actually computed — the disaggregated bench's
        # "decode replica ran ZERO prefill chunks" gate reads these
        self.exported_kv = 0
        self.imported_kv = 0
        self.imported_kv_bytes = 0
        self.prefill_chunk_calls = 0
        # observability: `trace` is an optional serving.trace.TraceSink
        # (per-request timelines — prefill chunk / retire events emit
        # through it, keyed by rid); the flight recorder is ALWAYS on —
        # one bounded host-side record per step tick, written BEFORE
        # the device call so a failing tick is the last record in the
        # ring. Imported lazily like the prefix cache: trace.py is
        # dependency-free but lives in serving/, and nlp must not pull
        # the serving package eagerly.
        from ..serving.profiling import StepProfiler
        from ..serving.trace import FlightRecorder, TraceSink
        # sampled device-time attribution: every Nth device-call tick
        # (profile_sample_every; 0 disables) is fenced with
        # block_until_ready and its device wall lands in bounded
        # per-shape histograms — see _profile_t0/_profile_commit for
        # the documented SYNC001 sample gate
        self.profiler = StepProfiler(sample_every=profile_sample_every)
        if trace is True:
            # mirror the engine's bool API: True means "a default sink"
            trace = TraceSink()
        elif trace is False:
            trace = None
        elif trace is not None and not hasattr(trace, "emit"):
            # reject now, not as an AttributeError mid-step that would
            # surface as a device failure and abort in-flight requests
            raise TypeError(
                f"trace must be a serving.trace.TraceSink, True/False, "
                f"or None — got {type(trace).__name__}")
        self._trace = trace
        self.flight = FlightRecorder(cap=flight_recorder_cap)
        nb = num_blocks or (max_batch * self.M)
        if prefix_cache:
            # vLLM-style automatic prefix caching: a trie over full-block
            # token contents + a refcounted pool, so admissions sharing a
            # prompt prefix reuse its KV blocks and prefill only their
            # suffix (serving/cache.py has the subsystem overview).
            # Imported here, not at module top: cache.py is dependency-
            # free but lives in serving/, and this module must not pull
            # the serving package eagerly (serving -> nlp is the lazy
            # direction the engine already relies on)
            from ..serving.cache import PrefixCacheIndex
            self._pcache: "Optional[PrefixCacheIndex]" = \
                PrefixCacheIndex(block_size)
            self.alloc: BlockAllocator = RefcountingBlockAllocator(
                nb, on_evict=self._pcache.evict)
        else:
            self._pcache = None
            self.alloc = BlockAllocator(nb)
        kp, vp, ksc, vsc = init_pool(cfg, nb, block_size,
                                     kv_dtype=self.kv_dtype)
        self.cache = PagedKVCache(
            kp, vp, jnp.zeros((max_batch, self.M), jnp.int32),
            jnp.zeros((max_batch,), jnp.int32), ksc, vsc)
        if self._mesh is not None:
            self.cache = self._pin_cache_shardings(self.cache)
        self.active = [False] * max_batch
        self.slot_req: List[Optional[int]] = [None] * max_batch
        self.slot_blocks: List[Optional[List[int]]] = [None] * max_batch
        self.slot_tokens: List[Optional[List[int]]] = [None] * max_batch
        self.budget = [0] * max_batch
        self.stop = [-1] * max_batch          # per-slot stop id (-1 = none)
        # device mirrors of (active, budget, stop): the decode chunk both
        # consumes and RETURNS them, so steady-state decoding re-uploads
        # nothing (SYNC001) — admission/retirement null the mirror and the
        # next step refreshes it from the host lists
        self._dev_state = None
        self.cur_tok = jnp.zeros((max_batch,), jnp.int32)
        self.queue: List = []
        self.outputs: Dict[int, List[int]] = {}
        self._next_rid = 0
        self._chunk_fn = None
        self._delivered: Dict[int, int] = {}   # rid -> tokens handed out
        self._just_finished: List[int] = []

    def submit(self, tokens, stop_token_id: Optional[int] = None,
               max_new_tokens: Optional[int] = None,
               speculative: Optional[bool] = None) -> int:
        """Queue a request. `stop_token_id` finishes THIS request early
        when emitted (in addition to the batcher-wide eos); the slot's
        blocks return to the pool on finish. `max_new_tokens` caps this
        request's budget (must be <= the batcher-wide max — the block
        table width is sized for it). `speculative=False` opts THIS
        request out of the spec pipeline (its verify rows ride along
        with acceptance forced to 0, i.e. plain greedy decode — the
        engine's quarantine fallback for victims of a failed spec
        tick); None inherits the batcher default."""
        toks = list(map(int, tokens))
        mn = self.validate(len(toks), max_new_tokens)
        rid = self._next_rid
        self._next_rid += 1
        stop = -1 if stop_token_id is None else int(stop_token_id)
        if speculative is False:
            self._no_spec.add(rid)
        self.queue.append((rid, toks, stop, mn))
        self.outputs[rid] = []
        self._delivered[rid] = 0
        return rid

    def validate(self, prompt_len: int,
                 max_new_tokens: Optional[int] = None) -> int:
        """Check a request's shape against this batcher's static sizing;
        returns the resolved max_new budget. The ONE place the sizing
        rules live — submit() and the serving layer both use it."""
        mn = self.max_new if max_new_tokens is None else int(max_new_tokens)
        if not 1 <= mn <= self.max_new:
            raise ValueError(
                f"max_new_tokens {mn} out of range [1, {self.max_new}]")
        if prompt_len + mn > self.max_total:
            raise ValueError(
                f"prompt of {prompt_len} + max_new {mn} exceeds "
                f"max_total_len {self.max_total}")
        return mn

    def blocks_needed(self, prompt_len: int,
                      max_new_tokens: Optional[int] = None,
                      tokens: Optional[Sequence[int]] = None) -> int:
        """Pool blocks a request of this shape takes FROM the pool while
        in flight. With `tokens` and prefix caching on, blocks the cache
        already holds live (refcount >= 1, pinned by another in-flight
        request) don't count — admission shares them instead of
        allocating. Cached refcount-0 matches DO still count: reviving
        one consumes a unit of `free_blocks` (free + cached) just like a
        fresh allocation, so the defer logic's `needed <= free_blocks`
        comparison stays exact either way."""
        mn = self.max_new if max_new_tokens is None else int(max_new_tokens)
        need = -(-(prompt_len + mn) // self.bs)
        if tokens is not None and self._pcache is not None:
            matched, _, _ = self._match_cached(list(tokens))
            need -= sum(1 for b in matched if self.alloc.refcount(b) > 0)
        # NOTE: block COUNTS are kv_dtype-invariant by construction —
        # the int8 scale pool is indexed by the same block ids (one
        # scale slot per pool block, allocated and freed with it), so
        # cached-aware deferral admits identically under "fp" and
        # "int8". What changes is bytes per block: kv_block_bytes()
        # below is the single source for that, scale overhead included.
        return need

    # -- quantized-serving byte accounting --------------------------------
    def kv_block_bytes(self) -> int:
        """HBM bytes ONE pool block occupies (all layers, K+V pools,
        int8 scale-pool overhead included) — quantization.kv's
        kv_block_bytes under this batcher's geometry and kv_dtype."""
        cfg = self.cfg
        return kvq.kv_block_bytes(
            cfg.num_hidden_layers, self.bs, cfg.num_key_value_heads,
            cfg.head_dim, self.kv_dtype,
            fp_itemsize=jnp.dtype(cfg.dtype).itemsize)

    def kv_pool_bytes(self) -> int:
        """Total KV pool footprint: capacity blocks x kv_block_bytes()
        — equals the device arrays' nbytes sum (asserted in tests)."""
        return self.alloc.num_blocks * self.kv_block_bytes()

    def kv_cached_bytes(self) -> int:
        """Bytes held by reclaimable (refcount-0, prefix-cached) blocks
        — the reusable-KV share of the pool a router/dashboard reads."""
        return self.alloc.stats().get("cached_blocks", 0) \
            * self.kv_block_bytes()

    def kv_bytes_per_token(self) -> float:
        """HBM bytes one cached token costs (and one decode-step gather
        moves per live token): kv_block_bytes / block_size. The bench's
        quantized gate asserts int8 <= 0.55x fp on this number."""
        return self.kv_block_bytes() / self.bs

    def weight_bytes(self) -> int:
        """Resident parameter bytes (codes + scales for a w8 tree) —
        host-side .nbytes sum, no device sync."""
        return sum(x.nbytes for x in jax.tree_util.tree_leaves(self.params))

    def _match_cached(self, toks: List[int]
                      ) -> Tuple[List[int], int, Optional[int]]:
        """Prefix-cache lookup for a prompt: (matched block chain,
        cached token count, copy-on-write source block or None).

        Full-block matches are shared as-is. When the match covers the
        WHOLE prompt there is no suffix left to prefill, yet sampling
        needs the last position's logits — so the final matched block is
        demoted to a copy-on-write source: admission copies its KV into
        a private block and recomputes only the prompt's last token
        there (cached length P-1), instead of recomputing the whole
        block. The partially-filled tail is thus never shared."""
        if self._pcache is None:
            return [], 0, None
        matched = self._pcache.match(toks)
        cached_len = len(matched) * self.bs
        cow_src = None
        if matched and cached_len == len(toks):
            cow_src = matched[-1]
            matched = matched[:-1]
            cached_len = len(toks) - 1
        return matched, cached_len, cow_src

    def prefix_cached_tokens(self, tokens: Sequence[int]) -> int:
        """Prompt tokens the prefix cache can serve RIGHT NOW (0 with the
        cache off). Cheap trie walk, no refcount moves — the scheduler's
        cache-aware admission preference reads this."""
        if self._pcache is None:
            return 0
        _, cached_len, _ = self._match_cached(list(tokens))
        return cached_len

    @property
    def prefill_buckets(self) -> Tuple[int, ...]:
        """The prefill bucket ladder (empty = bucketing disabled)."""
        return self._buckets

    @property
    def prefill_compile_count(self) -> int:
        """Distinct prefill shapes compiled so far — standalone (group,
        bucket, phase) AND fused (rows, bucket) executables. Flat after
        warmup is the whole point of bucketing: each shape compiles
        exactly once for the batcher's lifetime."""
        return len(self._prefill_cache) + len(self._fused_cache)

    @property
    def compile_count(self) -> int:
        """EVERY compiled device-step shape: the prefill/fused ladder
        plus the plain decode chunk executable plus the speculative
        draft/verify pair. The zero-post-warmup-recompiles gate reads
        this one — a decode-only stretch after a fused stretch must
        not compile either (the chunk fn used to slip through
        `prefill_compile_count`, compiling lazily on the first
        standalone-decode step)."""
        return (self.prefill_compile_count + len(self._chunk_cache)
                + len(self._spec_cache))

    def prefix_stats(self) -> Dict[str, Any]:
        """Prefix-cache counters for the serving metrics surface:
        hits/misses/hit_tokens/hit_rate from the index plus the
        allocator's cached-block and eviction counts. `enabled` False
        (and nothing else) when the batcher runs without the cache."""
        if self._pcache is None:
            return {"enabled": False}
        d: Dict[str, Any] = {"enabled": True}
        d.update(self._pcache.stats())
        astats = self.alloc.stats()
        d["cached_blocks"] = astats.get("cached_blocks", 0)
        d["evictions"] = astats.get("evicted_blocks", 0)
        return d

    def release(self, rid: int) -> None:
        """Drop a finished/aborted request's retained output list. The
        long-lived serving engine calls this once tokens are delivered —
        without it `outputs` grows with every request ever served.
        (Standalone run() callers read outputs afterwards, so the
        batcher never drops entries on its own.)"""
        self.outputs.pop(rid, None)
        self._delivered.pop(rid, None)

    def free_slots(self) -> int:
        """Batch slots available to new admissions. Queued-but-not-yet-
        prefilled requests count as taken, and so do slots reserved by
        a prepared admission whose (possibly multi-chunk, mid-stream)
        prefill has not committed yet — without the pending term a
        fused admission landing during a chunked prefill could
        oversubscribe max_batch. Never negative: callers may queue past
        capacity directly via submit(), but a slot deficit still means
        zero slots for anyone new."""
        return max(0, self.active.count(False) - len(self.queue)
                   - len(self._pending))

    def abort(self, rid: int) -> bool:
        """Cancel a request: drop it from the queue, or retire its slot
        mid-decode so its blocks return to the pool immediately. Already-
        generated tokens stay in `outputs`. Returns False when rid is
        unknown or already finished."""
        for i, entry in enumerate(self.queue):
            if entry[0] == rid:
                del self.queue[i]
                self._delivered.pop(rid, None)
                self._no_spec.discard(rid)
                return True
        for i, (rec, _done) in enumerate(self._pending):
            if rec.rid == rid:
                # prepared (possibly mid-stream chunked prefill): undo
                # like a failed prefill — unlink index registrations and
                # return the blocks; any KV already written there is
                # dead content in freed blocks
                self._rollback([rec])
                del self._pending[i]
                self._delivered.pop(rid, None)
                self._no_spec.discard(rid)
                self._requeue_poisoned(rec)
                return True
        for slot in range(self.B):
            if self.active[slot] and self.slot_req[slot] == rid:
                self._retire(slot)
                # an abort is the caller's bookkeeping, not a completion
                self._just_finished.remove(rid)
                self._delivered.pop(rid, None)
                return True
        return False

    def _requeue_poisoned(self, rec: "_Admission") -> None:
        """Aborting the pending `rec` unlinked and freed `rec.inserted`
        before anyone wrote their KV; a co-pending record whose matched
        chain (or COW source) leans on those blocks would skip
        prefilling a prefix NO ONE will ever compute — silent garbage
        tokens. Roll back the pending tail from the first such record
        and push the requests back onto the queue front (original
        order), so the next drain re-prepares them against the real
        index state. Requeueing the whole tail keeps admission order
        and absorbs cascades (a rolled-back record's own insertions
        poison later matches too). Safe to fully undo: only the head
        record can be mid-stream, and the head was prepared before
        `rec`, so every tail record's prefill has not started."""
        poisoned = set(rec.inserted)
        cut = None
        for i, (sib, _done) in enumerate(self._pending):
            refs = set(sib.matched)
            if sib.cow_src is not None:
                refs.add(sib.cow_src)
            if refs & poisoned:
                cut = i
                break
        if cut is None:
            return
        victims = [e[0] for e in self._pending[cut:]]
        self._rollback(victims)
        del self._pending[cut:]
        for v in victims:
            # timeline visibility for the cascade: without this event a
            # rolled-back sibling's re-preparation looks like a second
            # unexplained "prepared" in trace_report
            self._trace_emit(v.rid, "requeued",
                             reason="poisoned_sibling")
        self.queue[:0] = [(v.rid, v.toks, v.stop, v.mn) for v in victims]

    # -- KV transfer (serving/kvtransfer.py holds the container) ----------
    def kv_fingerprint(self) -> Dict[str, Any]:
        """Model/pool-shape identity a KVSnapshot must match to be
        importable here — kvtransfer.check_compatible compares these
        key-for-key so a cross-topology mistake (different model,
        kv_dtype or block size) fails at the handoff boundary instead
        of scattering misinterpreted codes into the pool."""
        return {
            "num_layers": int(self.cfg.num_hidden_layers),
            "num_key_value_heads": int(self.cfg.num_key_value_heads),
            "head_dim": int(self.cfg.head_dim),
            "block_size": self.bs,
            "kv_dtype": self.kv_dtype,
            "pool_dtype": str(self.cache.k.dtype),
        }

    def export_kv(self, rid: int):
        """Snapshot an in-flight request's paged KV into a portable
        host container (serving.kvtransfer.KVSnapshot): ONE coalesced
        device_get over exactly the blocks its chain has written —
        never the whole pool — plus the matching int8 scale entries
        and the host bookkeeping (tokens, remaining budget, stop id)
        an `import_kv` needs to resume decode elsewhere.

        Only an ACTIVE decode slot is exportable: queued/pending
        requests have no KV worth moving (re-submitting the prompt is
        strictly cheaper), and finished ones have released their
        blocks — ValueError for both. Migration boundary, not the
        decode hot path: the device pull below IS the transfer."""
        slot = None
        for s in range(self.B):
            if self.active[s] and self.slot_req[s] == rid:
                slot = s
                break
        if slot is None:
            raise ValueError(
                f"request {rid} holds no active decode slot — only "
                f"in-flight decode state is exportable")
        gen = list(self.outputs.get(rid, []))
        prompt = list(self.slot_tokens[slot] or [])
        # the last emitted token's KV is not written yet (decode writes
        # token t's KV while producing t+1) — the same arithmetic
        # _retire uses when registering the prefix
        written = len(prompt) + len(gen) - 1
        # ptlint: disable=SYNC001 — one guard readback at the migration boundary, never per step
        if written != int(self.cache.lengths[slot]):
            raise RuntimeError(
                f"slot {slot} device length diverged from host "
                f"bookkeeping — mid-commit state is not exportable")
        nw = -(-written // self.bs)
        chain = list(self.slot_blocks[slot][:nw])
        idx = jnp.asarray(chain)
        pulls = [self.cache.k[:, idx], self.cache.v[:, idx]]
        if self.cache.k_scale is not None:
            pulls += [self.cache.k_scale[:, idx],
                      self.cache.v_scale[:, idx]]
        # ptlint: disable=SYNC001 — the coalesced chain gather IS the export
        host = jax.device_get(tuple(pulls))
        ks, vs = (host[2], host[3]) if len(host) == 4 else (None, None)
        from ..serving.kvtransfer import KVSnapshot
        snap = KVSnapshot(
            k=host[0], v=host[1], k_scale=ks, v_scale=vs,
            tokens=prompt + gen, prompt_len=len(prompt),
            budget=int(self.budget[slot]),
            stop_token_id=int(self.stop[slot]),
            tail_valid=written - (nw - 1) * self.bs,
            fingerprint=self.kv_fingerprint(),
            src_blocks=chain, src_replica=self.replica_id)
        self.exported_kv += 1
        self._trace_emit(rid, "exported", slot=slot, blocks=nw,
                         bytes=snap.nbytes, tokens=len(snap.tokens))
        return snap

    def import_blocks_needed(self, snap) -> int:
        """Pool blocks `import_kv(snap)` will draw — the head-of-line
        check an engine's import queue runs before popping. Matches the
        source batcher's own sizing: written + the unwritten last token
        + the remaining budget is exactly P + max_new there."""
        return -(-(len(snap.tokens) + int(snap.budget)) // self.bs)

    def import_kv(self, snap, speculative: bool = False,
                  on_rid=None) -> int:
        """Adopt a KVSnapshot: allocate a fresh chain, scatter the
        block codes AND their int8 scales (transferred entries keep
        their exact scales; the unwritten tail blocks get the 0.0
        never-written sentinel, exactly like _prepare_admission's
        fresh-block reset — grow-only rescale discipline intact),
        register the written full blocks in the prefix index so
        siblings hit, and activate a slot that resumes decode at
        len(tokens) with ZERO prefill chunks. Host-side .at[].set pool
        edits only — no compiled-shape memo key moves, so post-warmup
        recompiles stay 0. Returns the new rid; its outputs list is
        pre-seeded with the snapshot's generated tokens and
        `_delivered` already covers them, so nothing re-emits.

        `speculative=False` (default) opts the imported request out of
        the spec pipeline: the draft state did not travel, and plain
        greedy decode keeps cross-hop bitwise parity unconditionally
        (spec is greedy-identical by construction, so True is safe too
        — the default just removes the reasoning burden).

        `on_rid` (optional) is called with the assigned rid before any
        trace event fires — the engine uses it to alias the rid onto
        the request's trace timeline.

        Raises ValueError on fingerprint/shape mismatch and
        RuntimeError when no slot or blocks are free — callers gate on
        `free_slots()` / `import_blocks_needed()` first."""
        from ..serving import kvtransfer
        problems = kvtransfer.check_compatible(snap.fingerprint,
                                               self.kv_fingerprint())
        if problems:
            raise ValueError(
                "KV snapshot incompatible with this batcher: "
                + "; ".join(problems))
        toks = [int(t) for t in snap.tokens]
        P = int(snap.prompt_len)
        gen = toks[P:]
        budget = int(snap.budget)
        if not gen:
            raise ValueError(
                "snapshot carries no generated token — export happens "
                "at or after the first decode commit")
        if budget < 1:
            raise ValueError(
                "snapshot budget exhausted — the source should have "
                "retired this request, nothing to resume")
        written = len(toks) - 1
        nw = -(-written // self.bs)
        if nw != int(snap.k.shape[1]):
            raise ValueError(
                f"snapshot carries {int(snap.k.shape[1])} blocks but "
                f"its {written} written tokens span {nw}")
        total = written + 1 + budget      # == P + max_new at the source
        if total > self.max_total:
            raise ValueError(
                f"resumed request needs {total} total tokens, over "
                f"this batcher's max_total_len {self.max_total}")
        need = -(-total // self.bs)
        reserved = {e[0].slot for e in self._pending}
        slot = None
        for s in range(self.B):
            if not self.active[s] and s not in reserved:
                slot = s
                break
        if slot is None:
            raise RuntimeError("no free batch slot for KV import")
        if need > self.alloc.free_blocks:
            raise RuntimeError(
                f"KV import needs {need} blocks, pool has "
                f"{self.alloc.free_blocks} free")
        fresh = self.alloc.allocate(need)
        # scatter the chain's codes into the fresh blocks — the same
        # host-side .at[].set idiom as _apply_cow, nothing traced
        hk, hv = snap.k, snap.v
        idx = jnp.asarray(fresh[:nw])
        cache = self.cache._replace(
            k=self.cache.k.at[:, idx].set(
                jnp.asarray(hk, self.cache.k.dtype)),
            v=self.cache.v.at[:, idx].set(
                jnp.asarray(hv, self.cache.v.dtype)))
        if cache.k_scale is not None:
            # fingerprint equality guarantees the snapshot carries
            # scales whenever the local pool is quantized
            hks, hvs = snap.k_scale, snap.v_scale
            sks = jnp.zeros((cache.k_scale.shape[0], need), jnp.float32)
            sks = sks.at[:, :nw].set(jnp.asarray(hks, jnp.float32))
            svs = jnp.zeros((cache.v_scale.shape[0], need), jnp.float32)
            svs = svs.at[:, :nw].set(jnp.asarray(hvs, jnp.float32))
            fidx = jnp.asarray(fresh)
            cache = cache._replace(
                k_scale=cache.k_scale.at[:, fidx].set(sks),
                v_scale=cache.v_scale.at[:, fidx].set(svs))
        row = fresh + [0] * (self.M - need)
        self.cache = cache._replace(
            table=cache.table.at[slot].set(jnp.asarray(row, jnp.int32)),
            lengths=cache.lengths.at[slot].set(written))
        rid = self._next_rid
        self._next_rid += 1
        if on_rid is not None:
            # caller hook fired the moment the rid exists — the engine
            # aliases rid→trace timeline here so the "imported" emit
            # below lands on the request's timeline instead of
            # auto-opening a phantom rid lane
            on_rid(rid)
        self.outputs[rid] = list(gen)
        self._delivered[rid] = len(gen)
        self.active[slot] = True
        self.slot_req[slot] = rid
        self.slot_blocks[slot] = list(fresh)
        self.slot_tokens[slot] = toks[:P]
        self.budget[slot] = budget
        self.stop[slot] = int(snap.stop_token_id)
        self.cur_tok = self.cur_tok.at[slot].set(gen[-1])
        self._dev_state = None           # slot occupancy changed
        self._spec_ok_dev = None
        if not speculative:
            self._no_spec.add(rid)
        if self._pcache is not None:
            # the written prefix's full blocks (prompt AND generated,
            # like _retire's registration) become visible to siblings
            # immediately; their KV is already written, so mark_cached
            # now — the post-_commit discipline, not the prepared one
            n_full = written // self.bs
            if n_full:
                self.alloc.mark_cached(self._pcache.insert(
                    toks[:n_full * self.bs], fresh[:n_full]))
        self.imported_kv += 1
        self.imported_kv_bytes += snap.nbytes
        self._trace_emit(rid, "imported", slot=slot, blocks=need,
                         bytes=snap.nbytes, resumed_tokens=len(gen),
                         src_replica=snap.src_replica)
        return rid

    # -- internals --------------------------------------------------------
    def _upload_slot_state(self):
        """Host slot lists → device arrays. Deliberately OUTSIDE step()'s
        hot path: it runs only when admission/retirement invalidated the
        mirror, so lock-step decode pays zero host→device uploads."""
        # ptlint: disable=SYNC001 — this IS the cached-mirror refresh
        # the rule asks for: it uploads only when admission/retirement
        # invalidated `_dev_state`, never per decode step
        return (jnp.asarray(self.active),
                jnp.asarray(self.budget, jnp.int32),  # ptlint: disable=SYNC001 — mirror refresh (see above)
                jnp.asarray(self.stop, jnp.int32))  # ptlint: disable=SYNC001 — mirror refresh (see above)

    # -- observability (host-side bookkeeping ONLY: no device values,
    #    no syncs — SYNC001's derived hot set covers them) ----------------
    def _trace_emit(self, rid: int, kind: str, dur=None, **attrs) -> None:
        """Emit one per-request trace event (no-op without a sink).
        Every attr must already be a plain host value — a jax array
        here would be a hidden device sync on the hot path."""
        if self._trace is not None:
            self._trace.emit(rid, kind, dur=dur, **attrs)

    def _trace_chunks(self, items, bucket: int, fused: bool,
                      dur: float, device_dur=None) -> None:
        """Emit one prefill_chunk event per packed row: which suffix
        span ran, at which bucket (and what padding that cost), fused
        onto the decode chunk or standalone, cold or continuing — and,
        on the FIRST chunk, how many prompt tokens the prefix cache
        skipped (the cached-prefix skip the timeline makes visible).
        `device_dur` (seconds) rides along when the sampled profiler
        fenced this call: the chunk's DEVICE wall next to its host
        wall, so a capture window's timelines attribute regressions to
        the kernel vs host scheduling."""
        self.prefill_chunk_calls += len(items)
        if self._trace is None:
            return
        for rec, start, end in items:
            extra = {} if device_dur is None \
                else {"device_dur": round(device_dur, 6)}
            self._trace.emit(
                rec.rid, "prefill_chunk", dur=dur, slot=rec.slot,
                start=start, end=end, bucket=bucket,
                pad=bucket - (end - start), fused=fused, cold=start == 0,
                cached_tokens=rec.cached_len if start == rec.cached_len
                else 0, **extra)

    def _record_tick(self, mode: str, **fields) -> None:
        """Append one flight-recorder record for this step tick: the
        scheduler's decision plus pool/queue state, recorded BEFORE the
        device call so the tick that raises is the ring's last record."""
        self.flight.record(
            mode, active_slots=sum(self.active),
            queue_depth=len(self.queue), pending=len(self._pending),
            free_slots=self.free_slots(),
            free_blocks=self.alloc.free_blocks, **fields)

    def _profile_t0(self):
        """The sampled-profiler gate, taken once per device-call tick:
        returns a perf_counter start time when THIS tick is fenced
        (every `profile_sample_every`th tick, or any tick of an armed
        capture window), None otherwise. The unfenced path is one
        locked counter bump — no device work, no syncs."""
        return time.perf_counter() if self.profiler.should_fence() \
            else None

    def _profile_commit(self, t0, outputs, *, mode: str, bucket: int,
                        units: int, rids) -> Optional[float]:
        """Fence an ALREADY-ISSUED device call and attribute its walls:
        host_s is dispatch wall (the call returning control), device_s
        is call-start → block_until_ready completion. Records into the
        profiler's per-(mode, bucket, units, impl, qkey) histograms
        and, when a sink is attached, a device-lane trace span so
        timelines carry device wall next to host wall. Returns
        device_s, or None for an unfenced tick.

        THE DOCUMENTED SYNC001 SAMPLE GATE: `jax.block_until_ready`
        here is a deliberate host↔device sync — one fenced step in
        `profile_sample_every`, never in the unfenced path, and the
        compiled-shape memo keys never see the profiler (zero
        post-warmup recompiles holds with sampling on — gated by
        `bench_serving.py --slo`)."""
        if t0 is None:
            return None
        host_s = time.perf_counter() - t0
        jax.block_until_ready(outputs)
        device_s = time.perf_counter() - t0
        self.profiler.record(
            mode=mode, bucket=int(bucket), units=int(units),
            impl=self.attention_impl, weight_dtype=self.weight_dtype,
            kv_dtype=self.kv_dtype, device_s=device_s, host_s=host_s,
            detail={"rids": [int(r) for r in rids]})
        if self._trace is not None:
            self._trace.span(
                "device." + mode, dur=device_s, lane="device",
                mode=mode, bucket=int(bucket), units=int(units),
                host_s=round(host_s, 6), impl=self.attention_impl,
                replica_id=self.replica_id)
        return device_s

    def _gate(self, mode: str, rids, probe: bool = False) -> None:
        """Fault-injection hook at the device-call boundary: a no-op in
        production (no injector), the chaos harness's seam in tests and
        `bench_serving.py --chaos`. Called AFTER `_record_tick` so an
        injected failure's tick is the flight ring's last record, like
        a real device fault's would be."""
        if self._fault is not None:
            self._fault.check(mode, rids, probe=probe)

    # -- bucketed / chunked / batched prefill -----------------------------
    def _bucket_for(self, S: int) -> int:
        """Smallest ladder bucket that fits a suffix of S tokens; with
        bucketing disabled (empty ladder) the bucket IS the exact length."""
        for b in self._buckets:
            if b >= S:
                return b
        return S

    def _suffix_chunks(self, cached_len: int,
                       P: int) -> List[Tuple[int, int, int]]:
        """Split the still-to-prefill suffix [cached_len, P) into
        (start, end, bucket) chunks: largest-bucket-sized pieces first,
        then one bucketed remainder — bounding per-chunk latency and
        lifting the effective prompt length past one flash pass."""
        out: List[Tuple[int, int, int]] = []
        start = cached_len
        cap = self._buckets[-1] if self._buckets else P - cached_len
        while P - start > cap:
            out.append((start, start + cap, cap))
            start += cap
        out.append((start, P, self._bucket_for(P - start)))
        return out

    def _group_pad(self, G: int) -> int:
        """Pad an admission group to the next power of two (capped at the
        batch width) so burst sizes draw from a fixed shape ladder."""
        return min(_pow2_ceil(max(1, G)), self.B)

    def _mesh_axis(self) -> str:
        """The TP mesh axis name the step builders hand to the
        shard_map-wrapped kernel ("mp" when mesh is off — the kwarg is
        dead then, since `self._mesh` is None)."""
        return "mp" if self._mesh_cfg is None else self._mesh_cfg.axis

    def _build_prefill(self, cold: bool):
        """The one traced prefill: rows [G, Pb] at per-row absolute
        positions against the shared pool. Pure — compile bookkeeping
        lives host-side in `_prefill_exe` (TRACE001)."""
        cfg, impl = self.cfg, self.attention_impl
        mesh, max_ = self._mesh, self._mesh_axis()

        def prefill(params, rows, k, v, ks, vs, table, positions, valid,
                    lengths):
            sub = PagedKVCache(k, v, table, lengths, ks, vs)
            logits, sub = forward_paged(params, rows, sub, positions,
                                        valid, cfg, is_prefill=cold,
                                        attention_impl=impl, mesh=mesh,
                                        mesh_axis=max_)
            return logits, sub.k, sub.v, sub.k_scale, sub.v_scale

        return jax.jit(prefill)

    def _prefill_exe(self, G: int, Pb: int, cold: bool):
        """Memoized COMPILED prefill per (group, bucket, phase) shape.
        AOT-lowered from abstract avals, so `warmup_prefill` can populate
        the whole ladder without running a single FLOP; steady-state
        admission dispatches straight to a compiled executable and never
        retraces."""
        key = (G, Pb, cold, self.attention_impl) + self._skey \
            + self._qkey + self._mkey
        exe = self._prefill_cache.get(key)
        if exe is None:
            fn = self._prefill_fns.get(cold)
            if fn is None:
                fn = self._build_prefill(cold)
                self._prefill_fns[cold] = fn
            sds, i32 = self._aval, jnp.int32
            pstruct = self._pstruct()
            exe = fn.lower(
                pstruct, sds((G, Pb), i32),
                sds(self.cache.k.shape, self.cache.k.dtype,
                    self._shard_pool),
                sds(self.cache.v.shape, self.cache.v.dtype,
                    self._shard_pool),
                self._scale_aval(self.cache.k_scale),
                self._scale_aval(self.cache.v_scale),
                sds((G, self.M), i32), sds((G, Pb), i32),
                sds((G, Pb), jnp.bool_), sds((G,), i32)).compile()
            self._prefill_cache[key] = exe
        return exe

    # -- mesh-aware AOT lowering avals ------------------------------------
    def _aval(self, shape, dtype, sharding=None):
        """ShapeDtypeStruct for AOT lowering. With a serving mesh on,
        every aval carries a committed sharding (`sharding` None =
        replicated) so the compiled executable's input layout is
        pinned; mesh off lowers the plain aval — identical programs,
        byte-identical memo keys."""
        if self._mesh is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        return jax.ShapeDtypeStruct(
            shape, dtype,
            sharding=self._shard_repl if sharding is None else sharding)

    def _pstruct(self):
        """Param aval tree for lowering — per-leaf TP shardings when
        the mesh is on (serving.tp's table)."""
        if self._mesh is None:
            return jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype),
                self.params)
        return jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype,
                                              sharding=s),
            self.params, self._shard_params)

    def _cstruct(self):
        """PagedKVCache aval tree: pools on the head axis, block
        table / lengths / int8 scale pools replicated."""
        c = self.cache
        return PagedKVCache(
            self._aval(c.k.shape, c.k.dtype, self._shard_pool),
            self._aval(c.v.shape, c.v.dtype, self._shard_pool),
            self._aval(c.table.shape, c.table.dtype),
            self._aval(c.lengths.shape, c.lengths.dtype),
            self._scale_aval(c.k_scale), self._scale_aval(c.v_scale))

    def _pin_cache_shardings(self, cache: PagedKVCache) -> PagedKVCache:
        """Pin a fresh cache's leaves to their serving-mesh shardings
        (the committed layout every compiled step expects; eager pool
        edits — COW copies, import scatters — preserve it)."""
        put = jax.device_put
        return PagedKVCache(
            put(cache.k, self._shard_pool),
            put(cache.v, self._shard_pool),
            put(cache.table, self._shard_repl),
            put(cache.lengths, self._shard_repl),
            None if cache.k_scale is None
            else put(cache.k_scale, self._shard_repl),
            None if cache.v_scale is None
            else put(cache.v_scale, self._shard_repl))

    def _scale_aval(self, scale):
        """AOT-lowering aval for a scale pool: None (no leaves — the fp
        pool's lowered signature is unchanged) or the [L, N] f32 shape
        (replicated under a serving mesh — per-(layer, block) scales
        carry no head axis)."""
        return None if scale is None else \
            self._aval(jnp.shape(scale), scale.dtype)

    def warmup_prefill(self, buckets: Optional[Sequence[int]] = None,
                       group_sizes: Optional[Sequence[int]] = None,
                       modes: Sequence[bool] = (True, False),
                       fused: Optional[bool] = None) -> int:
        """Pre-compile every device-step shape serving can hit — each
        ladder bucket x each power-of-two group size x {cold, cached},
        plus (with fusion on) the fused decode+prefill variant per
        reachable prefill-row count (units x group pad, units up to
        `fused_units`), plus EVERY reachable decode chunk executable
        (today: the one configured standalone-decode chunk) — via AOT
        lowering (no device compute). After this, steady state never
        compiles: not admission, not a fused stretch, and not the first
        decode-only step after one. Returns the number of newly
        compiled shapes. With bucketing disabled only the decode chunk
        warms (exact prefill shapes are unbounded; there is nothing
        finite to ladder)."""
        ladder = self._buckets if buckets is None else tuple(buckets)
        if group_sizes is None:
            # exactly the shapes _group_pad can ever produce
            group_sizes = {self._group_pad(g) for g in range(1, self.B + 1)}
        n0 = self.compile_count
        for Pb in ladder:
            for G in sorted(set(group_sizes)):
                for cold in modes:
                    self._prefill_exe(int(G), int(Pb), bool(cold))
        warm_fused = self._fused if fused is None else fused
        if warm_fused:
            # total prefill rows a fused call can carry: U consecutive
            # same-bucket units, each padded to the SAME power-of-two
            # group size — the memo normalizes (units, group) to the
            # row count U*G, so coinciding shapes compile once. Only
            # REACHABLE shapes warm: every pending record holds a slot
            # and a fused step needs >= 1 ACTIVE decode slot besides,
            # so a call whose widest unit pads to G (> G//2 records)
            # riding with u-1 more units (>= 1 record each) exists only
            # when that minimum record count fits in max_batch - 1
            rows = set()
            for G in sorted(set(int(g) for g in group_sizes)):
                need_widest = G // 2 + 1 if G > 1 else 1
                for u in range(1, self.fused_units + 1):
                    if need_widest + (u - 1) <= self.B - 1:
                        rows.add(u * G)
            for Pb in ladder:
                for Gt in sorted(rows):
                    self._fused_exe(Gt, int(Pb))
        # the standalone-decode chunk is reachable from ANY workload
        # (incl. a decode-only stretch after a fused stretch) — warm it
        # regardless of ladder/fusion configuration
        self._chunk_exe()
        if self.speculative:
            # the spec draft/verify pair runs every non-fused decode
            # tick — warm both so a spec stretch never retraces
            self._spec_draft_exe()
            self._spec_verify_exe()
        return self.compile_count - n0

    def _prepare_admission(self, slot: int, rid: int, toks: List[int],
                           stop: int, max_new: Optional[int],
                           quiet: bool = False) -> _Admission:
        """Blocks + prefix-cache bookkeeping for one admission, NO model
        compute: share the matched chain, allocate the rest, apply the
        COW clone, and register the prompt's full blocks so same-burst
        siblings hit. The slot stays inactive until `_commit`."""
        P = len(toks)
        mn = self.max_new if max_new is None else max_new
        need = -(-(P + mn) // self.bs)
        # prefix cache: share the matched chain (bumping refcounts — and
        # pinning the COW source so allocate() can't evict it before the
        # copy), then allocate only what the cache didn't supply
        matched, cached_len, cow_src = self._match_cached(toks)
        if cow_src is not None and self.alloc.refcount(cow_src) == 0:
            # a cached (refcount-0) COW source is transiently revived
            # ALONGSIDE its fresh clone — one pool unit more than
            # blocks_needed() promises the defer check. When the pool
            # can't afford it, degrade to recomputing the final block
            # cold instead of blowing up an admission that was told it
            # fits (a live source costs nothing extra: sharing it takes
            # no unit from the pool). Peak draw = fresh allocations +
            # every refcount-0 match revived off the cached list + the
            # transient source.
            draw = (need - len(matched)
                    + sum(1 for b in matched
                          if self.alloc.refcount(b) == 0))
            if self.alloc.free_blocks < draw + 1:
                cow_src = None
                cached_len = len(matched) * self.bs
        pinned = matched + ([cow_src] if cow_src is not None else [])
        if pinned:
            self.alloc.share(pinned)
        try:
            fresh = self.alloc.allocate(need - len(matched))
        except Exception:
            if pinned:
                self.alloc.release(pinned)
            raise
        if self.kv_dtype == "int8" and fresh:
            # a recycled block keeps its previous tenant's scale (free
            # is host-side bookkeeping); writing under that inflated
            # scale would quantize this request's KV coarser than a
            # fresh block would — reset to the never-written sentinel
            # so quantization depends only on what THIS request writes
            # (warm == cold stays by construction, whatever the pool's
            # reuse history). Admission path, not the decode hot path.
            idx = jnp.asarray(fresh)
            self.cache = self.cache._replace(
                k_scale=self.cache.k_scale.at[:, idx].set(0.0),
                v_scale=self.cache.v_scale.at[:, idx].set(0.0))
        # NOTE: the copy-on-write clone (fresh[0] <- pool[cow_src]) is
        # NOT applied here — a same-burst neighbor may have registered
        # the source block moments ago with its prefill still pending,
        # so the clone must wait until every earlier unit has written
        # the pool (`_apply_cow` in `_run_standalone_unit` /
        # `_step_fused`)
        inserted: List[int] = []
        if self._pcache is not None:
            # register the prompt's FULL blocks right away so requests
            # queued behind this one (same burst included) share them
            # while it is still in flight; `mark_cached` waits for
            # `_commit` so a failed prefill can't park unwritten KV on
            # the reclaimable list
            n_full = P // self.bs
            if n_full:
                owned = matched + fresh
                inserted = self._pcache.insert(toks[:n_full * self.bs],
                                               owned[:n_full])
        chunks = self._suffix_chunks(cached_len, P)
        if not quiet:       # probes re-prepare without timeline noise
            self._trace_emit(rid, "prepared", slot=slot, prompt_len=P,
                             cached_tokens=cached_len,
                             cow=cow_src is not None, blocks=need,
                             chunks=len(chunks),
                             weight_dtype=self.weight_dtype,
                             kv_dtype=self.kv_dtype,
                             kv_block_bytes=self.kv_block_bytes(),
                             replica_id=self.replica_id,
                             # fast-path attribution: resolved backend,
                             # spec score path and mesh degree — so a
                             # mixed fleet's trace artifacts say which
                             # replicas actually ran the kernel paths
                             attention_impl=self.attention_impl,
                             spec_backend=(self.spec_attention_impl
                                           if self.speculative
                                           else None),
                             mesh_tp=(1 if self._mesh_cfg is None
                                      else int(self._mesh_cfg.tp)))
        return _Admission(slot, rid, list(toks), stop, mn, need, matched,
                          cached_len, cow_src, fresh, inserted, chunks)

    def _rollback(self, recs: Sequence[_Admission]) -> None:
        """Undo prepared-but-uncommitted admissions after a failed
        prefill: unlink their index registrations (nothing may match KV
        that was never written), then return their blocks. Never touches
        committed slots."""
        for rec in recs:
            if self._pcache is not None:
                for b in rec.inserted:
                    self._pcache.unlink(b)
            self.alloc.release(rec.fresh)
            pinned = rec.matched + ([rec.cow_src]
                                    if rec.cow_src is not None else [])
            if pinned:
                self.alloc.release(pinned)

    def _pack_prefill_rows(self, items: Sequence[Tuple[_Admission, int,
                                                       int]],
                           Pb: int, Gp: int):
        """Pack a unit's (record, start, end) chunks into the [Gp, Pb]
        prefill-row arrays one compiled call consumes: rows pad to the
        bucket, the group pads to its power-of-two size, padding masks
        through `valid` (writes drop) and clamped positions (gathers
        stay in range). Returns (rows, pos, valid, table, last_idx) and
        accounts the pad overhead."""
        rows = np.zeros((Gp, Pb), np.int32)
        pos = np.zeros((Gp, Pb), np.int32)
        val = np.zeros((Gp, Pb), np.bool_)
        tab = np.zeros((Gp, self.M), np.int32)
        li = np.zeros((Gp,), np.int32)
        real = 0
        maxpos = self.M * self.bs - 1
        for g, (rec, start, end) in enumerate(items):
            S = end - start
            real += S
            rows[g, :S] = rec.toks[start:end]
            pos[g] = np.minimum(np.arange(start, start + Pb), maxpos)
            val[g, :S] = True
            tab[g, :rec.need] = rec.matched + rec.fresh
            li[g] = S - 1
        self.prefill_pad_tokens += Gp * Pb - real
        return rows, pos, val, tab, li

    def _prefill_call(self, items: Sequence[Tuple[_Admission, int, int]],
                      Pb: int, cold: bool):
        """Run ONE compiled standalone prefill over a unit's rows.
        Returns (logits [Gp, Pb, V], last real index per row [Gp])."""
        Gp = self._group_pad(len(items))
        rows, pos, val, tab, li = self._pack_prefill_rows(items, Pb, Gp)
        exe = self._prefill_exe(Gp, Pb, cold)
        logits, k, v, ks, vs = exe(
            self.params, jnp.asarray(rows), self.cache.k, self.cache.v,
            self.cache.k_scale, self.cache.v_scale, jnp.asarray(tab),
            jnp.asarray(pos), jnp.asarray(val),
            jnp.zeros((Gp,), jnp.int32))
        self.cache = self.cache._replace(k=k, v=v, k_scale=ks, v_scale=vs)
        return logits, li

    def _units(self,
               recs: Sequence[_Admission]) -> List[List[_Admission]]:
        """Partition a burst into execution units: single-chunk records
        with the same (bucket, phase) batch into one prefill call; a
        chunked record runs alone (its chunks are sequential by
        construction).

        Group-growing admission (the PR 4 follow-on): a record no
        longer has to be CONSECUTIVE with its bucket-mates — it joins
        the EARLIEST open same-key unit with room, provided moving it
        earlier jumps over no unit whose registered blocks it depends
        on. The dependency set is the record's shared-prefix chain
        (matched blocks) plus its COW source: dependencies only point
        at EARLIER submissions, and later records that depend on THIS
        one only ever see it move toward them, so the reorder preserves
        every write-before-read edge and greedy tokens are
        schedule-invariant (tests/test_fused_step.py pins this).

        A COW record still never shares a unit with the record that
        registered its source block: the clone reads the POOL (outside
        the compiled call), so the source's prefill has to complete in
        an earlier unit first. Matched (non-COW) blocks are safe
        in-unit — the gather sees the layer's writes inside the
        computation."""
        units: List[List[_Admission]] = []
        # per unit: the growable key (None = closed chunked unit) and
        # the pool blocks its records registered
        keys: List[Optional[Tuple]] = []
        inserted: List[set] = []
        for rec in recs:
            if len(rec.chunks) > 1:
                units.append([rec])
                keys.append(None)
                inserted.append(set(rec.inserted))
                continue
            s, _, b = rec.chunks[0]
            k = (b, s == 0)
            deps = set(rec.matched)
            if rec.cow_src is not None:
                deps.add(rec.cow_src)
            # blocks registered AFTER each candidate slot, scanned
            # back to front: joining unit i is legal iff no unit past
            # i registered a block this record depends on
            target = None
            after: set = set()
            for i in range(len(units) - 1, -1, -1):
                if keys[i] == k and len(units[i]) < self.B \
                        and not (deps & after) \
                        and not (rec.cow_src is not None
                                 and rec.cow_src in inserted[i]):
                    target = i
                elif deps & after:
                    break
                after |= inserted[i]
            if target is not None:
                units[target].append(rec)
                inserted[target].update(rec.inserted)
            else:
                units.append([rec])
                keys.append(k)
                inserted.append(set(rec.inserted))
        return units

    def _apply_cow(self, unit: Sequence[_Admission]) -> None:
        """Apply a unit's copy-on-write clones right before its prefill:
        every earlier unit has written the pool by now, so the clone
        captures the source block's real KV (fresh[0] sits at chain
        position len(matched) — exactly the clone's slot in the table
        row)."""
        for rec in unit:
            if rec.cow_src is not None:
                dst = rec.fresh[0]
                self.cache = self.cache._replace(
                    k=self.cache.k.at[:, dst].set(
                        self.cache.k[:, rec.cow_src]),
                    v=self.cache.v.at[:, dst].set(
                        self.cache.v[:, rec.cow_src]))
                if self.cache.k_scale is not None:
                    # int8 pool: the clone's codes are meaningless
                    # without the source block's dequant scales
                    self.cache = self.cache._replace(
                        k_scale=self.cache.k_scale.at[:, dst].set(
                            self.cache.k_scale[:, rec.cow_src]),
                        v_scale=self.cache.v_scale.at[:, dst].set(
                            self.cache.v_scale[:, rec.cow_src]))

    def _commit(self, rec: _Admission, first: int) -> None:
        """Activate a successfully prefilled admission in its slot."""
        for start, end, _b in rec.chunks:
            # real (pre-padding) chunk lengths, the distribution a
            # workload-specific ladder is fitted from (bucket_tuner).
            # Recorded at commit, not prepare: rolled-back and aborted
            # admissions must not feed phantom chunks to the fit.
            self.prefill_suffix_hist[end - start] = \
                self.prefill_suffix_hist.get(end - start, 0) + 1
        if rec.cow_src is not None:
            self.alloc.release([rec.cow_src])  # pinned only for the copy
        P = len(rec.toks)
        if self._pcache is not None:
            self._pcache.note_admission(P, rec.cached_len)
            if rec.inserted:
                self.alloc.mark_cached(rec.inserted)
        owned = rec.matched + rec.fresh
        blocks = owned + [0] * (self.M - rec.need)
        self.cache = self.cache._replace(
            table=self.cache.table.at[rec.slot].set(
                jnp.asarray(blocks, jnp.int32)),
            lengths=self.cache.lengths.at[rec.slot].set(P))
        self.cur_tok = self.cur_tok.at[rec.slot].set(first)
        self.active[rec.slot] = True
        self.slot_req[rec.slot] = rec.rid
        self.slot_blocks[rec.slot] = owned
        self.slot_tokens[rec.slot] = list(rec.toks)
        self.budget[rec.slot] = rec.mn - 1
        self.stop[rec.slot] = rec.stop
        self._dev_state = None        # host slot state diverged from device
        self._spec_ok_dev = None      # slot occupancy changed
        self.outputs[rec.rid].append(first)
        if ((self.eos is not None and first == self.eos)
                or first == rec.stop or self.budget[rec.slot] <= 0):
            self._retire(rec.slot)

    def _unit_view(self, unit, entries):
        """One pending unit as an execution view — the unit-shape logic
        shared by the standalone and fused poppers: ([pipeline entries],
        [(rec, start, end) rows], bucket, cold, final). A chunked record
        runs its CURRENT chunk (progress lives in its entry); `final` is
        False for a non-last chunk — the entry stays pending with its
        progress bumped — and True means every record in the unit
        commits when the call lands."""
        if len(unit[0].chunks) > 1:
            rec, done = entries[0]
            start, end, bucket = rec.chunks[done]
            return (entries[:1], [(rec, start, end)], bucket, start == 0,
                    done == len(rec.chunks) - 1)
        items = [(r, r.chunks[0][0], r.chunks[0][1]) for r in unit]
        _, _, bucket = unit[0].chunks[0]
        return entries, items, bucket, items[0][1] == 0, True

    def _pop_unit(self):
        """The next prefill execution unit off the pending pipeline —
        group-growing admission means a unit's records need not be a
        contiguous slice of the pending list, so entries resolve by
        record identity."""
        unit = self._units([e[0] for e in self._pending])[0]
        entry_of = {id(e[0]): e for e in self._pending}
        return self._unit_view(unit, [entry_of[id(r)] for r in unit])

    def _finish_unit(self, entries, firsts) -> None:
        """Commit a unit whose FINAL chunk just computed: one readback
        of every first token at once, then activate each record."""
        # ptlint: disable=SYNC001 — the unit's single coalesced
        # readback (docstring): one sync per prefill unit, not per token
        firsts = np.asarray(firsts)
        for entry, first in zip(entries, firsts):
            self._commit(entry[0], int(first))
            self._pending.remove(entry)

    def _run_standalone_unit(self) -> None:
        """Run ONE standalone prefill call for the head pending unit —
        the PR4 path: nothing decodes while it runs, so it only ever
        executes when the decode set is empty (nothing to stall) or
        fusion is off (`decode_stall_steps` then counts the cost)."""
        entries, items, bucket, cold, final = self._pop_unit()
        Gp = self._group_pad(len(items))
        unit_rids = [r.rid for r, _, _ in items]
        self._record_tick(
            "prefill", rids=unit_rids, bucket=bucket,
            group_pad=Gp, cold=cold, final=final,
            stalls_decode=any(self.active),
            compile_hit=(Gp, bucket, cold, self.attention_impl)
            + self._skey + self._qkey + self._mkey
            in self._prefill_cache)
        self._gate("prefill", unit_rids)
        t0 = time.perf_counter()
        self._apply_cow([e[0] for e in entries if e[1] == 0])
        t_prof = self._profile_t0()
        logits, li = self._prefill_call(items, bucket, cold)
        dev_s = self._profile_commit(
            t_prof, (logits, self.cache.k, self.cache.v),
            mode="prefill", bucket=bucket,
            units=self._group_pad(len(items)), rids=unit_rids)
        if final:
            # ragged last-token logits per row, ONE readback per unit
            # (inside _finish_unit) — li came packed with the rows
            g = len(items)
            last = jnp.argmax(
                logits[jnp.arange(g), jnp.asarray(li[:g])], axis=-1)
            self._finish_unit(entries, last)
        else:
            entries[0][1] += 1
        self._trace_chunks(items, bucket, fused=False,
                           dur=time.perf_counter() - t0,
                           device_dur=dev_s)

    def _fail_pending(self) -> None:
        """A failed prefill/fused call must not leak blocks OR silently
        drop work: every still-pending record rolls back (the slots
        were never activated, so nothing else would ever free them) and
        requeues at the FRONT of the batcher queue in original order —
        the caller decides who actually dies (the engine's quarantine
        probes the requeued records and re-admits the innocent; its
        fail-all fallback aborts them, which pops queue entries too).
        All-or-nothing on purpose — later records may lean on the
        failed unit's registered blocks, so partial survival would
        strand never-written KV."""
        victims = [e[0] for e in self._pending]
        self._rollback(victims)
        self._pending.clear()
        # no "requeued" trace event here: the DECISION about these
        # records (quarantine victim / culprit / fail-all) belongs to
        # the caller, which emits exactly one event per request — a
        # second one from the rollback would double trace_report's
        # requeue counts against health()["requests_requeued"]
        self.queue[:0] = [(v.rid, v.toks, v.stop, v.mn) for v in victims]

    def _prefill_pending(self) -> None:
        """Drain the pending pipeline with standalone prefill calls
        (chunked records stream their remaining chunks back to back).
        With fusion ON the drain stops the moment a commit activates a
        decode slot — running the rest standalone would stall that
        fresh decoder exactly the way fusion exists to avoid, so the
        remaining units piggyback on the following fused steps instead.
        With fusion off everything drains (the PR4 path) and each call
        made while slots decode counts a stall. A failed call must not
        leak blocks: every still-pending record rolls back — the slots
        were never activated, so nothing else would ever free them."""
        try:
            while self._pending:
                if any(self.active):
                    if self._fused:
                        break          # the fused step takes it from here
                    # every in-flight slot stalls behind this call — the
                    # cost fusion exists to remove
                    self.decode_stall_steps += 1
                self._run_standalone_unit()
        except Exception:
            self._fail_pending()
            raise

    # -- quarantine probes (engine-thread only, failure path only) --------
    def probe_decode_slot(self, slot: int) -> None:
        """Re-run the failed tick's decode chunk for ONE slot in
        isolation: the chunk executable runs with every other slot
        masked inactive, so only this slot's computation can raise.
        Commits NOTHING — the returned cache/tokens are discarded (the
        engine requeues the innocent for a warm re-prefill instead),
        and per-request paged attention makes the masked run exercise
        exactly this slot's math. Raises whatever the device (or the
        fault injector) raises; returning means the slot is clean.
        Failure-path only: never called on the hot path."""
        rid = self.slot_req[slot]
        self._gate("probe", [rid], probe=True)
        act = [False] * self.B
        act[slot] = True
        out = self._chunk_exe()(
            self.params, self.cache, self.cur_tok, jnp.asarray(act),
            self.cache.lengths, jnp.asarray(self.budget, jnp.int32),
            jnp.asarray(self.stop, jnp.int32))
        # force the async dispatch so a data-dependent device failure
        # surfaces HERE, attributed to this slot (probe verdicts are
        # the one consumer of these arrays — nothing is kept)
        jax.block_until_ready(out)

    def probe_queued(self, rid: int) -> None:
        """Re-run a QUEUED request's first prefill chunk in isolation:
        prepare its blocks, run one standalone single-record prefill
        call (a warmed (1, bucket) ladder shape), then roll everything
        back — the queue entry, the pool and the prefix index end
        exactly as they were. A failed prefill/fused call requeues its
        pending records (`_fail_pending`), so this is how the engine's
        quarantine re-executes the failing tick's prefill units one
        record at a time. Raises what the device raises; a pool too
        tight to re-prepare returns silently (inconclusive is NOT a
        conviction). No-op for a rid not in the queue."""
        entry = next((e for e in self.queue if e[0] == rid), None)
        if entry is None:
            return
        _, toks, stop, mn = entry
        self._gate("probe", [rid], probe=True)
        try:
            rec = self._prepare_admission(-1, rid, toks, stop, mn,
                                          quiet=True)
        except RuntimeError:
            return        # pool exhausted mid-quarantine: inconclusive
        try:
            start, end, bucket = rec.chunks[0]
            self._apply_cow([rec])
            logits, _ = self._prefill_call([(rec, start, end)], bucket,
                                           cold=start == 0)
            jax.block_until_ready(logits)
        finally:
            self._rollback([rec])

    def _pop_fused_units(self):
        """Select the units ONE fused call carries, in unit order (the
        group-grown `_units` partition, which preserves every
        dependency edge): the head unit always rides; up to
        `fused_units - 1` more units join when each (a) prefills this
        step at the head unit's bucket (one compiled shape), and (b)
        holds no block reference — matched chain or COW source — that
        an earlier SELECTED unit registered but will not have fully
        written.
        In-call pool writes ARE visible to the gather (each layer
        writes every row's KV before gathering), so a later unit may
        chain onto blocks a completing co-selected unit writes this
        very call; but a chunked unit advancing a NON-final chunk
        leaves its later blocks unwritten, and the host-side COW clone
        copies the pool BEFORE the call — both force the dependent unit
        to wait for a later step. Returns (groups, bucket): groups is a
        list of (pipeline entries, (rec, start, end) items, final) per
        selected unit."""
        units = self._units([e[0] for e in self._pending])
        entry_of = {id(e[0]): e for e in self._pending}
        groups: List[Tuple[List, List, bool]] = []
        bucket0 = None
        inserted_sel: set = set()    # registered by any selected unit
        unwritten: set = set()       # ... that this call won't write
        for unit in units:
            if len(groups) >= self.fused_units:
                break
            entries, items, bucket, _cold, final = self._unit_view(
                unit, [entry_of[id(r)] for r in unit])
            if bucket0 is None:
                bucket0 = bucket
            elif bucket != bucket0:
                break
            refs = set()
            cow_refs = set()
            for rec in unit:
                refs.update(rec.matched)
                if rec.cow_src is not None:
                    cow_refs.add(rec.cow_src)
            if (refs | cow_refs) & unwritten or cow_refs & inserted_sel:
                break
            groups.append((entries, items, final))
            for rec in unit:
                inserted_sel.update(rec.inserted)
                if not final:
                    # mid-stream: blocks past this chunk stay unwritten
                    unwritten.update(rec.inserted)
        return groups, bucket0

    def _step_fused(self):
        """Piggyback up to `fused_units` pending prefill units on this
        step's decode chunk: ONE compiled call advances every active
        slot by its chunk AND prefills the selected same-bucket
        admission chunks. Returns the decode chunk's tokens [B, chunk]
        (host copy)."""
        try:
            groups, bucket = self._pop_fused_units()
            # every selected unit pads to the SAME group size so the
            # call's shape is (units x Gp, bucket) — drawn from the
            # finite warmed ladder whatever mix of units rides
            Gp = max(self._group_pad(len(items))
                     for _, items, _ in groups)
            decode_rids = [self.slot_req[s] for s in range(self.B)
                           if self.active[s]]
            unit_rids = [[r.rid for r, _, _ in items]
                         for _, items, _ in groups]
            self._record_tick(
                "fused", units=unit_rids, decode_rids=decode_rids,
                bucket=bucket, group_pad=Gp, rows=len(groups) * Gp,
                compile_hit=(len(groups) * Gp, bucket,
                             self.attention_impl) + self._skey
                + self._qkey + self._mkey in self._fused_cache)
            self._gate("fused",
                       decode_rids + [r for u in unit_rids for r in u])
            t0 = time.perf_counter()
            self._apply_cow([e[0] for entries, _, _ in groups
                             for e in entries if e[1] == 0])
            packs = [self._pack_prefill_rows(items, bucket, Gp)
                     for _, items, _ in groups]
            rows, pos, val, tab, li = (
                np.concatenate([p[i] for p in packs], axis=0)
                for i in range(5))
            exe = self._fused_exe(len(groups) * Gp, bucket)
            if self._dev_state is None:
                self._dev_state = self._upload_slot_state()
            active, budget, stop = self._dev_state
            t_prof = self._profile_t0()
            (k, v, ks, vs, lengths, tok, budget, active, toks,
             pfirst) = exe(
                self.params, self.cache.k, self.cache.v,
                self.cache.k_scale, self.cache.v_scale,
                self.cache.table, self.cache.lengths, self.cur_tok,
                active, budget, stop, jnp.asarray(rows),
                jnp.asarray(pos), jnp.asarray(val), jnp.asarray(tab),
                jnp.asarray(li))
            dev_s = self._profile_commit(
                t_prof, (k, v, toks, pfirst), mode="fused",
                bucket=bucket, units=len(groups),
                rids=decode_rids + [r for u in unit_rids for r in u])
            # one host sync serves BOTH the decode chunk's tokens and
            # the prefill rows' first tokens — and, dispatch being
            # async, surfaces any device-side failure HERE, before the
            # batcher state commits below
            toks, pfirst = jax.device_get((toks, pfirst))  # ptlint: disable=SYNC001 — single per-step sync, decode + prefill readbacks coalesced
        except Exception:
            # decode state untouched (the assignments below never ran)
            self._fail_pending()
            raise
        self.cache = self.cache._replace(k=k, v=v, k_scale=ks,
                                         v_scale=vs, lengths=lengths)
        self.cur_tok = tok
        self._dev_state = (active, budget, stop)
        self.fused_steps += 1
        self.fused_unit_count += len(groups)
        fused_dur = time.perf_counter() - t0
        # commit IN ORDER: group g's real rows sit at [g*Gp, g*Gp+|items|)
        # of the concatenated prefill batch, so pfirst slices per group
        for g, (entries, items, final) in enumerate(groups):
            if final:
                self._finish_unit(entries,
                                  pfirst[g * Gp:g * Gp + len(items)])
            else:
                entries[0][1] += 1
            self._trace_chunks(items, bucket, fused=True, dur=fused_dur,
                               device_dur=dev_s)
        return toks

    def _retire(self, slot: int) -> None:
        rid = self.slot_req[slot]
        blocks = self.slot_blocks[slot]
        self._trace_emit(rid, "retired", slot=slot,
                         generated=len(self.outputs.get(rid, [])))
        if self._pcache is not None:
            # register the finished sequence's FULL blocks (prompt +
            # generated) before releasing: at refcount 0 they park on
            # the cached LRU instead of dying, so the next request with
            # this prefix skips their prefill. The last emitted token's
            # KV was never written (decode writes token t's KV while
            # producing t+1), so the written length is P + m - 1.
            gen = self.outputs.get(rid, [])
            prompt = self.slot_tokens[slot] or []
            kv_len = len(prompt) + max(0, len(gen) - 1)
            n_full = kv_len // self.bs
            if n_full:
                seq = (prompt + gen)[:n_full * self.bs]
                self.alloc.mark_cached(
                    self._pcache.insert(seq, blocks[:n_full]))
            # leaf-first into the LRU: a chain's deep blocks are evicted
            # before the prefix blocks other chains may still extend
            self.alloc.release(list(reversed(blocks)))
        else:
            self.alloc.free(blocks)
        self._just_finished.append(rid)
        self.active[slot] = False
        self.slot_req[slot] = None
        self.slot_blocks[slot] = None
        self.slot_tokens[slot] = None
        self.stop[slot] = -1
        self._dev_state = None        # host slot state diverged from device
        self._spec_ok_dev = None      # slot occupancy changed
        self._no_spec.discard(rid)

    def _drain_queue(self) -> None:
        """Prepare queued requests into the pending-prefill pipeline
        while a batch slot AND the KV blocks fit. Slots reserved by
        still-pending admissions are NOT handed out again (a mid-stream
        chunked prefill keeps its slot across steps)."""
        reserved = {e[0].slot for e in self._pending}
        free = [s for s in range(self.B)
                if not self.active[s] and s not in reserved]
        recs: List[_Admission] = []
        try:
            while free and self.queue:
                _, toks0, _, mn0 = self.queue[0]
                # cached-aware: blocks another in-flight request already
                # pins for this prompt's prefix are shared, not drawn
                # from the pool — and `free_blocks` already counts
                # reclaimable cached blocks on the refcounting allocator.
                # Earlier records in this burst already hold their blocks
                # (and registered their prompts), so the head-of-line
                # check and the trie walk both see them.
                need = self.blocks_needed(len(toks0), mn0, tokens=toks0)
                if need > self.alloc.free_blocks:
                    if (not any(self.active) and not recs
                            and not self._pending):
                        # nothing in flight will ever free blocks
                        raise RuntimeError(
                            f"request needs {need} blocks but the pool "
                            f"holds only {self.alloc.num_blocks} — size "
                            f"num_blocks for the largest single request")
                    break           # defer until a request retires
                rid, toks, stop, mn = self.queue.pop(0)
                recs.append(self._prepare_admission(
                    free.pop(0), rid, toks, stop, mn))
        except Exception:
            self._rollback(recs)
            raise
        for rec in recs:
            self._pending.append([rec, 0])

    def _fuse_now(self) -> bool:
        """This step's scheduling decision: piggyback the next pending
        prefill unit on the decode chunk exactly when there IS pending
        prefill work, slots are decoding (someone to stall), and fusion
        is enabled. Everything else runs standalone."""
        return bool(self._fused and self._pending and any(self.active))

    def _admit(self) -> None:
        """Pull queued requests into the pending pipeline, then prefill
        standalone unless the next chunk will piggyback them: the decode
        set is empty (nothing to stall) or fusion is off (the PR4 path,
        stalls counted). Runs before AND after the device chunk so a
        retire frees slots for the same step's queue."""
        self._drain_queue()
        if self._pending and not self._fuse_now():
            self._prefill_pending()

    def _emit_one(self, logits_row, tok, act, lengths, budget, stop):
        """Greedy-emit one token per decode row and advance the row's
        state — THE stopping rule, shared by the decode scan body and
        the fused chunk's first token so the two cannot diverge (token
        parity between them is by construction)."""
        eos = -1 if self.eos is None else int(self.eos)
        nxt = jnp.argmax(logits_row, axis=-1).astype(jnp.int32)
        nxt = jnp.where(act, nxt, tok)
        lengths = lengths + act.astype(jnp.int32)
        budget = budget - act.astype(jnp.int32)
        # deactivate ON DEVICE the moment a slot's budget runs
        # out or it emits eos / its own stop id — a fixed-size
        # chunk must not keep writing past the slot's ALLOCATED
        # blocks (the table row's padding points at block 0,
        # i.e. someone else's cache)
        act = act & (budget > 0) & (nxt != eos) & (nxt != stop)
        return nxt, lengths, budget, act

    def _decode_step_body(self, params, stop):
        """The one traced single-token decode step, shared by the plain
        decode chunk AND the fused chunk's post-first-token scan."""
        cfg, impl = self.cfg, self.attention_impl
        mesh, max_ = self._mesh, self._mesh_axis()

        def step(carry, _):
            cache, tok, lengths, budget, act = carry
            pos = lengths[:, None]
            logits, cache = forward_paged(
                params, tok[:, None], cache, pos, act[:, None],
                cfg, is_prefill=False, attention_impl=impl, mesh=mesh,
                mesh_axis=max_)
            nxt, lengths, budget, act = self._emit_one(
                logits[:, 0], tok, act, lengths, budget, stop)
            # inactive slots must not drift: pin lengths ourselves
            cache = cache._replace(lengths=lengths)
            return (cache, nxt, lengths, budget, act), nxt

        return step

    def _build_chunk(self):
        chunk = self.chunk

        def run_chunk(params, cache, tok, active, lengths, budget, stop):
            step = self._decode_step_body(params, stop)
            (cache, tok, lengths, budget, act), toks = jax.lax.scan(
                step, (cache, tok, lengths, budget, active), None,
                length=chunk)
            # act/budget go back to the caller so the next chunk can feed
            # them in again without a host round-trip
            return cache, tok, lengths, budget, act, toks.T   # [B, chunk]

        return jax.jit(run_chunk)

    def _chunk_exe(self):
        """Memoized COMPILED plain decode chunk, AOT-lowered like the
        prefill shapes so `warmup_prefill` covers it — before this, the
        chunk fn compiled lazily on the first standalone-decode step,
        and a decode-only stretch AFTER a fused stretch (whose steps
        all ran `_fused_exe`) paid a post-warmup compile."""
        key = (self.chunk, self.attention_impl) + self._skey \
            + self._qkey + self._mkey
        exe = self._chunk_cache.get(key)
        if exe is None:
            if self._chunk_fn is None:
                self._chunk_fn = self._build_chunk()
            sds, i32 = self._aval, jnp.int32
            pstruct = self._pstruct()
            cstruct = self._cstruct()
            B = self.B
            exe = self._chunk_fn.lower(
                pstruct, cstruct, sds((B,), i32), sds((B,), jnp.bool_),
                sds((B,), i32), sds((B,), i32), sds((B,), i32)).compile()
            self._chunk_cache[key] = exe
        return exe

    def _build_fused(self):
        """The fused prefill+decode chunk: ONE compiled call over a
        mixed batch of `max_batch` decode rows plus `Pb` prefill-chunk
        rows (the Ragged Paged Attention mixed-mode shape). The first
        decode token and the whole prefill chunk compute in one
        forward_paged pass — decode rows are [.., Pb]-padded with only
        column 0 valid, prefill rows mask padding through valid /
        clamped positions exactly like the standalone path. Every row
        in the mixed batch takes the per-query-causal paged kernel,
        COLD prefill rows included (standalone cold prefill uses the
        flash path): the two compute the same softmax attention and
        greedy-token parity with the unfused path is asserted in
        tests/test_fused_step.py, but logits are not bit-for-bit.
        The remaining chunk-1 decode tokens scan the shared decode
        step body."""
        cfg, chunk, B = self.cfg, self.chunk, self.B
        impl = self.attention_impl
        mesh, max_ = self._mesh, self._mesh_axis()
        maxpos = self.M * self.bs - 1

        def run_fused(params, k, v, ks, vs, table, lengths, tok, active,
                      budget, stop, prows, ppos, pval, ptab, plast):
            Gp, Pb = prows.shape
            # decode rows ride the prefill chunk's width: token in
            # column 0 at the slot's current position, the rest padding
            # (writes drop; per-query attention keeps columns
            # independent, so column 0 is the P=1 decode computation)
            dtok = jnp.zeros((B, Pb), jnp.int32).at[:, 0].set(tok)
            dpos = jnp.minimum(
                lengths[:, None] + jnp.arange(Pb)[None, :], maxpos)
            dval = jnp.zeros((B, Pb), jnp.bool_).at[:, 0].set(active)
            sub = PagedKVCache(
                k, v, jnp.concatenate([table, ptab], 0),
                jnp.zeros((B + Gp,), jnp.int32), ks, vs)
            logits, sub = forward_paged(
                params, jnp.concatenate([dtok, prows], 0), sub,
                jnp.concatenate([dpos, ppos], 0),
                jnp.concatenate([dval, pval], 0), cfg, is_prefill=False,
                attention_impl=impl, mesh=mesh, mesh_axis=max_)
            # ragged last-token logits per prefill row → first tokens
            pfirst = jnp.argmax(logits[B:][jnp.arange(Gp), plast],
                                axis=-1).astype(jnp.int32)
            nxt, lengths, budget, active = self._emit_one(
                logits[:B, 0], tok, active, lengths, budget, stop)
            cache = PagedKVCache(sub.k, sub.v, table, lengths,
                                 sub.k_scale, sub.v_scale)
            step = self._decode_step_body(params, stop)
            (cache, tok, lengths, budget, active), toks = jax.lax.scan(
                step, (cache, nxt, lengths, budget, active), None,
                length=chunk - 1)
            toks = jnp.concatenate([nxt[None], toks], 0)
            return (cache.k, cache.v, cache.k_scale, cache.v_scale,
                    lengths, tok, budget, active,
                    toks.T, pfirst)                       # toks [B, chunk]

        return jax.jit(run_fused)

    def _fused_exe(self, Gp: int, Pb: int):
        """Memoized COMPILED fused chunk per (prefill rows, bucket)
        shape, AOT-lowered from abstract avals like `_prefill_exe` —
        warmup covers the whole fused ladder so steady-state
        piggybacked admission never retraces. `Gp` is the TOTAL prefill
        row count of the call: units x per-unit group pad for a
        multi-unit step, so (units, group) pairs with the same product
        share one executable."""
        key = (Gp, Pb, self.attention_impl) + self._skey + self._qkey \
            + self._mkey
        exe = self._fused_cache.get(key)
        if exe is None:
            if self._fused_fn is None:
                self._fused_fn = self._build_fused()
            sds, i32 = self._aval, jnp.int32
            pstruct = self._pstruct()
            B = self.B
            exe = self._fused_fn.lower(
                pstruct,
                sds(self.cache.k.shape, self.cache.k.dtype,
                    self._shard_pool),
                sds(self.cache.v.shape, self.cache.v.dtype,
                    self._shard_pool),
                self._scale_aval(self.cache.k_scale),
                self._scale_aval(self.cache.v_scale),
                sds((B, self.M), i32), sds((B,), i32), sds((B,), i32),
                sds((B,), jnp.bool_), sds((B,), i32), sds((B,), i32),
                sds((Gp, Pb), i32), sds((Gp, Pb), i32),
                sds((Gp, Pb), jnp.bool_), sds((Gp, self.M), i32),
                sds((Gp,), i32)).compile()
            self._fused_cache[key] = exe
        return exe

    # -- self-speculative decoding (draft k tokens, verify in one call,
    #    commit only the accepted rows) ------------------------------------
    def _spec_key(self, phase: str) -> Tuple:
        """Memo key for the spec `phase` ("draft" | "verify")
        executable — spec geometry + backend + quantization config.
        Carries `_skey` like every other compiled-shape memo key, so a
        batcher whose spec config changes shape (k, draft depth, tree
        branching, draft-w8) via the full spec tuple can never serve
        another config's executable; the resolved spec score-path
        backend rides inside `_skey` next to the geometry for the same
        reason (KEY001 enforces the convention)."""
        return (phase, self.spec_k, self._draft_depth,
                self.attention_impl) \
            + self._skey + self._qkey + self._mkey

    def spec_stats(self) -> Dict[str, Any]:
        """Speculative-decoding accounting: config + the SpecStats
        counters (steps / drafted / accepted / emitted, accept_rate,
        tokens_per_step). `enabled` False (and config only) when the
        batcher decodes plain."""
        d: Dict[str, Any] = {"enabled": self.speculative,
                             "backend": self.spec_attention_impl}
        d.update(self._spec_cfg.as_dict(self.cfg.num_hidden_layers))
        d.update(self.spec.as_dict())
        return d

    def _build_spec_draft(self):
        """The traced chain draft: spec_k autoregressive proposals per
        slot off the truncated layer stack, reading the committed pool
        READ-ONLY (layers 0..depth-1 of the target's pool ARE the
        draft's cache) with its own proposals riding the spec slab.
        `dlayers` is the draft-from-w8 quantized stack (None drafts
        from the target's own weights, sliced in-trace so XLA fuses
        the slice — no copy). Returns drafts [B, spec_k] (proposal
        j+1 per step j)."""
        cfg, K, depth, B = self.cfg, self.spec_k, self._draft_depth, \
            self.B
        maxpos = self.M * self.bs - 1
        impl = self.spec_attention_impl
        mesh, max_ = self._mesh, self._mesh_axis()

        def draft(params, dlayers, k, v, ks, vs, table, lengths, tok,
                  active):
            cache = PagedKVCache(k, v, table, lengths, ks, vs)
            layers = jax.tree_util.tree_map(
                lambda x: x[:depth], params["layers"]) \
                if dlayers is None else dlayers
            KVh, hd = cfg.num_key_value_heads, cfg.head_dim
            sk = jnp.zeros((depth, B, K, KVh, hd), cfg.dtype)
            sv = jnp.zeros_like(sk)

            def step(carry, j):
                tok, sk, sv = carry
                pos = jnp.minimum(lengths[:, None] + j, maxpos)
                logits, sk, sv = _forward_spec(
                    params, layers, tok[:, None], cache, pos, lengths,
                    sk, sv, j, cfg, impl=impl, mesh=mesh,
                    mesh_axis=max_)
                nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
                nxt = jnp.where(active, nxt, tok)
                return (nxt, sk, sv), nxt

            _, drafts = lax.scan(step, (tok, sk, sv),
                                 jnp.arange(K, dtype=jnp.int32))
            return drafts.T                              # [B, K]

        return jax.jit(draft)

    def _build_spec_tree_draft(self):
        """The traced TREE draft: level by level, one truncated-stack
        forward per level scores ALL of the level's nodes at once
        (each node's slab visibility is its ancestor path, so its
        logits equal the sequential prefix's) and lax.top_k proposes
        tree[j] children per node — child 0 is the node's argmax, so
        the tree always contains the chain draft's path. Level j's
        nodes land in slab rows [offs[j], offs[j+1]) — contiguous by
        the packed-level layout; the LAST level's proposals are never
        forwarded here (the verify computes their K/V). Returns
        drafts [B, spec_k] in slab-row order (levels concatenated)."""
        cfg, B, depth = self.cfg, self.B, self._draft_depth
        sc = self._spec_cfg
        tree = sc.tree
        D = len(tree)
        sizes, offs = sc.level_sizes(), sc.level_offsets()
        Sd = offs[D]                 # draft slab: root + levels 1..D-1
        maxpos = self.M * self.bs - 1
        impl = self.spec_attention_impl
        mesh, max_ = self._mesh, self._mesh_axis()
        A = sc.ancestor_mask()
        # per-level query visibility: the level's rows of the ancestor
        # mask, restricted to the draft slab's columns (static consts)
        vis_lv = [jnp.asarray([row[:Sd] for row in
                               A[offs[j]:offs[j + 1]]])
                  for j in range(D)]

        def draft(params, dlayers, k, v, ks, vs, table, lengths, tok,
                  active):
            cache = PagedKVCache(k, v, table, lengths, ks, vs)
            layers = jax.tree_util.tree_map(
                lambda x: x[:depth], params["layers"]) \
                if dlayers is None else dlayers
            KVh, hd = cfg.num_key_value_heads, cfg.head_dim
            sk = jnp.zeros((depth, B, Sd, KVh, hd), cfg.dtype)
            sv = jnp.zeros_like(sk)
            toks = tok[:, None]                    # level 0: the root
            out_levels = []
            for j in range(D):
                w = sizes[j]
                pos = jnp.broadcast_to(
                    jnp.minimum(lengths + j, maxpos)[:, None], (B, w))
                logits, sk, sv = _forward_spec(
                    params, layers, toks, cache, pos, lengths,
                    sk, sv, offs[j], cfg, vis=vis_lv[j], impl=impl,
                    mesh=mesh, mesh_axis=max_)
                # top-b children per node: lax.top_k ties break toward
                # the lower index, same as argmax — child 0 IS the
                # greedy continuation, so tree acceptance dominates
                # the chain's per sweep
                _, top = lax.top_k(logits, tree[j])  # [B, w, b]
                nxt = top.reshape(B, w * tree[j]).astype(jnp.int32)
                nxt = jnp.where(active[:, None], nxt, tok[:, None])
                out_levels.append(nxt)
                toks = nxt
            return jnp.concatenate(out_levels, axis=1)   # [B, spec_k]

        return jax.jit(draft)

    def _spec_dlayers_aval(self):
        """AOT-lowering aval tree for the draft-from-w8 stack (None —
        an empty pytree — when drafting from the target's weights)."""
        if self._spec_dlayers is None:
            return None
        return jax.tree_util.tree_map(
            lambda x: self._aval(jnp.shape(x), x.dtype),
            self._spec_dlayers)

    def _spec_draft_exe(self):
        """Memoized COMPILED draft step (chain or tree per the spec
        config), AOT-lowered like the prefill shapes so
        `warmup_prefill` covers it."""
        key = self._spec_key("draft")
        exe = self._spec_cache.get(key)
        if exe is None:
            if self._spec_draft_fn is None:
                self._spec_draft_fn = self._build_spec_tree_draft() \
                    if self.spec_tree is not None \
                    else self._build_spec_draft()
            sds, i32 = self._aval, jnp.int32
            pstruct = self._pstruct()
            B = self.B
            exe = self._spec_draft_fn.lower(
                pstruct, self._spec_dlayers_aval(),
                sds(self.cache.k.shape, self.cache.k.dtype,
                    self._shard_pool),
                sds(self.cache.v.shape, self.cache.v.dtype,
                    self._shard_pool),
                self._scale_aval(self.cache.k_scale),
                self._scale_aval(self.cache.v_scale),
                sds((B, self.M), i32), sds((B,), i32), sds((B,), i32),
                sds((B,), jnp.bool_)).compile()
            self._spec_cache[key] = exe
        return exe

    def _build_spec_verify(self):
        """The traced verify: score all spec_k+1 positions (cur_tok +
        the draft's proposals) in ONE full-depth pass over the
        read-only pool + spec slab, accept the longest prefix of
        proposals matching the target's own greedy tokens plus one
        corrected token (truncated by per-slot budget and eos/stop —
        the `_emit_one` stopping rule, vectorized over rows), then
        COMMIT: only the accepted rows' slab K/V reach the pool,
        written one row at a time in order so the int8 pool's
        grow-only per-block scales evolve exactly as sequential
        decode's would. Greedy output is identical to plain decode by
        construction — speculation changes the schedule, not the
        tokens."""
        cfg, K, B = self.cfg, self.spec_k, self.B
        P = K + 1
        eos = -1 if self.eos is None else int(self.eos)
        maxpos = self.M * self.bs - 1
        impl = self.spec_attention_impl
        mesh, max_ = self._mesh, self._mesh_axis()

        def verify(params, k, v, ks, vs, table, lengths, tok, drafts,
                   active, budget, stop, spec_ok):
            cache = PagedKVCache(k, v, table, lengths, ks, vs)
            toks_in = jnp.concatenate([tok[:, None], drafts], axis=1)
            pos = jnp.minimum(
                lengths[:, None] + jnp.arange(P)[None, :], maxpos)
            KVh, hd = cfg.num_key_value_heads, cfg.head_dim
            sk = jnp.zeros((cfg.num_hidden_layers, B, P, KVh, hd),
                           cfg.dtype)
            sv = jnp.zeros_like(sk)
            logits, sk, sv = _forward_spec(
                params, params["layers"], toks_in, cache, pos, lengths,
                sk, sv, jnp.int32(0), cfg, impl=impl, mesh=mesh,
                mesh_axis=max_)
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, P]
            # accept proposal i+1 while it equals the target's greedy
            # token at the previous position (longest matching prefix)
            match = (drafts == g[:, :K]).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1,
                            dtype=jnp.int32)
            n_acc = jnp.where(spec_ok, n_acc, 0)
            # emit g_0..g_{n_acc}, truncated at the budget and at the
            # first eos/stop emitted (tokens after an end never emit)
            idx = jnp.arange(P)[None, :]
            is_end = (g == eos) | (g == stop[:, None])
            ends_before = jnp.cumsum(is_end.astype(jnp.int32), axis=1) \
                - is_end.astype(jnp.int32)
            emit = (idx <= n_acc[:, None]) & (idx < budget[:, None]) \
                & (ends_before == 0) & active[:, None]
            n_emit = jnp.sum(emit, axis=1, dtype=jnp.int32)
            # verify-then-commit: ONLY accepted rows reach the pool —
            # row-sequential writes keep int8 scale growth identical
            # to plain decode's token-by-token commits
            ks2, vs2 = ks, vs
            for r in range(P):
                posr, valr = pos[:, r:r + 1], emit[:, r:r + 1]
                kr, vr = sk[:, :, r:r + 1], sv[:, :, r:r + 1]
                if ks is None:
                    k = jax.vmap(_write_pool,
                                 in_axes=(0, None, None, 0, None))(
                        k, table, posr, kr, valr)
                    v = jax.vmap(_write_pool,
                                 in_axes=(0, None, None, 0, None))(
                        v, table, posr, vr, valr)
                else:
                    k, ks2, _ = jax.vmap(
                        _write_pool_int8,
                        in_axes=(0, 0, None, None, 0, None))(
                        k, ks2, table, posr, kr, valr)
                    v, vs2, _ = jax.vmap(
                        _write_pool_int8,
                        in_axes=(0, 0, None, None, 0, None))(
                        v, vs2, table, posr, vr, valr)
            last = jnp.take_along_axis(
                g, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            last = jnp.where(active & (n_emit > 0), last, tok)
            budget2 = budget - n_emit
            active2 = active & (budget2 > 0) & (last != eos) \
                & (last != stop)
            return (k, v, ks2, vs2, lengths + n_emit, last, budget2,
                    active2, jnp.where(emit, g, 0), n_emit, n_acc)

        return jax.jit(verify)

    def _build_spec_tree_verify(self):
        """The traced TREE verify: score the whole packed token tree —
        root + every drafted node, slab visibility = the static
        ancestor mask — in ONE full-depth pass, then walk the tree
        level by level following the target's own greedy tokens: at
        each accepted node, the child whose draft token equals the
        target's greedy continuation extends the path (top-k children
        are distinct, so at most one matches — the same longest-
        matching-prefix rule as the chain, over a wider candidate
        set). The accepted path's rows — and ONLY those — commit
        row-sequentially exactly like the chain verify, so greedy
        output stays bit-identical to plain decode and the int8
        grow-only scale / prefix-cache invariants hold unchanged.
        Returns the chain verify's tuple with out/n_emit sized to the
        path width (tree depth + 1)."""
        cfg, B = self.cfg, self.B
        sc = self._spec_cfg
        tree = sc.tree
        D = len(tree)
        offs = sc.level_offsets()
        S = sc.slab_rows()
        P_out = D + 1
        eos = -1 if self.eos is None else int(self.eos)
        maxpos = self.M * self.bs - 1
        impl = self.spec_attention_impl
        mesh, max_ = self._mesh, self._mesh_axis()
        A = jnp.asarray(sc.ancestor_mask())                   # [S, S]
        lv = jnp.asarray(sc.row_levels(), jnp.int32)          # [S]

        def verify(params, k, v, ks, vs, table, lengths, tok, drafts,
                   active, budget, stop, spec_ok):
            cache = PagedKVCache(k, v, table, lengths, ks, vs)
            toks_in = jnp.concatenate([tok[:, None], drafts], axis=1)
            # every node sits at committed position lengths + level —
            # siblings share a position; visibility (the ancestor
            # mask), not position, separates them
            pos = jnp.minimum(lengths[:, None] + lv[None, :], maxpos)
            KVh, hd = cfg.num_key_value_heads, cfg.head_dim
            sk = jnp.zeros((cfg.num_hidden_layers, B, S, KVh, hd),
                           cfg.dtype)
            sv = jnp.zeros_like(sk)
            logits, sk, sv = _forward_spec(
                params, params["layers"], toks_in, cache, pos, lengths,
                sk, sv, jnp.int32(0), cfg, vis=A, impl=impl,
                mesh=mesh, mesh_axis=max_)
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
            # accept walk: cur = the path head's slab row, ci = its
            # index within its level; a level with no matching child
            # kills the walk (alive), exactly the chain's cumprod
            cur = jnp.zeros((B,), jnp.int32)
            ci = jnp.zeros((B,), jnp.int32)
            alive = spec_ok
            n_acc = jnp.zeros((B,), jnp.int32)
            path_rows = [cur]
            for j in range(1, D + 1):
                b = tree[j - 1]
                crows = offs[j] + ci[:, None] * b \
                    + jnp.arange(b)[None, :]                   # [B, b]
                ctoks = jnp.take_along_axis(toks_in, crows, axis=1)
                tgt = jnp.take_along_axis(g, cur[:, None], axis=1)
                hit = (ctoks == tgt) & alive[:, None]
                has = jnp.any(hit, axis=1)
                pick = jnp.argmax(hit, axis=1).astype(jnp.int32)
                ci2 = ci * b + pick
                cur = jnp.where(has, offs[j] + ci2, cur)
                ci = jnp.where(has, ci2, ci)
                n_acc = n_acc + has.astype(jnp.int32)
                alive = has
                path_rows.append(cur)
            path = jnp.stack(path_rows, axis=1)            # [B, D+1]
            # the emitted candidates: the target's greedy token after
            # each accepted path prefix (rows past n_acc duplicate the
            # head — masked off by emit below, never written)
            out_g = jnp.take_along_axis(g, path, axis=1)   # [B, D+1]
            idx = jnp.arange(P_out)[None, :]
            is_end = (out_g == eos) | (out_g == stop[:, None])
            ends_before = jnp.cumsum(is_end.astype(jnp.int32), axis=1) \
                - is_end.astype(jnp.int32)
            emit = (idx <= n_acc[:, None]) & (idx < budget[:, None]) \
                & (ends_before == 0) & active[:, None]
            n_emit = jnp.sum(emit, axis=1, dtype=jnp.int32)
            # verify-then-commit, identical to the chain: the accepted
            # path's positions are sequential (lengths + r), only its
            # rows' slab K/V reach the pool, one row at a time in
            # order — int8 scale growth matches sequential decode's
            pos_path = jnp.minimum(lengths[:, None] + idx, maxpos)
            ks2, vs2 = ks, vs
            for r in range(P_out):
                rowr = path[:, r][None, :, None, None, None]
                kr = jnp.take_along_axis(sk, rowr, axis=2)
                vr = jnp.take_along_axis(sv, rowr, axis=2)
                posr = pos_path[:, r:r + 1]
                valr = emit[:, r:r + 1]
                if ks is None:
                    k = jax.vmap(_write_pool,
                                 in_axes=(0, None, None, 0, None))(
                        k, table, posr, kr, valr)
                    v = jax.vmap(_write_pool,
                                 in_axes=(0, None, None, 0, None))(
                        v, table, posr, vr, valr)
                else:
                    k, ks2, _ = jax.vmap(
                        _write_pool_int8,
                        in_axes=(0, 0, None, None, 0, None))(
                        k, ks2, table, posr, kr, valr)
                    v, vs2, _ = jax.vmap(
                        _write_pool_int8,
                        in_axes=(0, 0, None, None, 0, None))(
                        v, vs2, table, posr, vr, valr)
            last = jnp.take_along_axis(
                out_g, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            last = jnp.where(active & (n_emit > 0), last, tok)
            budget2 = budget - n_emit
            active2 = active & (budget2 > 0) & (last != eos) \
                & (last != stop)
            return (k, v, ks2, vs2, lengths + n_emit, last, budget2,
                    active2, jnp.where(emit, out_g, 0), n_emit, n_acc)

        return jax.jit(verify)

    def _spec_verify_exe(self):
        """Memoized COMPILED verify step (AOT-lowered, warmup-covered)."""
        key = self._spec_key("verify")
        exe = self._spec_cache.get(key)
        if exe is None:
            if self._spec_verify_fn is None:
                self._spec_verify_fn = self._build_spec_tree_verify() \
                    if self.spec_tree is not None \
                    else self._build_spec_verify()
            sds, i32 = self._aval, jnp.int32
            pstruct = self._pstruct()
            B = self.B
            exe = self._spec_verify_fn.lower(
                pstruct,
                sds(self.cache.k.shape, self.cache.k.dtype,
                    self._shard_pool),
                sds(self.cache.v.shape, self.cache.v.dtype,
                    self._shard_pool),
                self._scale_aval(self.cache.k_scale),
                self._scale_aval(self.cache.v_scale),
                sds((B, self.M), i32), sds((B,), i32), sds((B,), i32),
                sds((B, self.spec_k), i32), sds((B,), jnp.bool_),
                sds((B,), i32), sds((B,), i32),
                sds((B,), jnp.bool_)).compile()
            self._spec_cache[key] = exe
        return exe

    def _step_spec(self):
        """One speculative decode tick: the draft proposes spec_k
        tokens per active slot off the truncated stack, the target
        verifies all k+1 positions in one call and commits only the
        accepted rows. Returns (out_toks [B, k+1], n_emit [B]) as host
        arrays — ONE host sync per tick, like the fused path."""
        decode_rids = [self.slot_req[s] for s in range(self.B)
                       if self.active[s]]
        if self._dev_state is None:
            self._dev_state = self._upload_slot_state()
        active, budget, stop = self._dev_state
        if self._spec_ok_dev is None:
            # per-slot spec participation (quarantine fallback: opted-
            # out victims decode plain through the same verify call) —
            # refreshed only when admit/retire changes slot occupancy
            self._spec_ok_dev = jnp.asarray(
                [self.slot_req[s] is not None
                 and self.slot_req[s] not in self._no_spec
                 for s in range(self.B)])
        c = self.cache
        self._record_tick(
            "spec_draft", rids=decode_rids, k=self.spec_k,
            compile_hit=self._spec_key("draft") in self._spec_cache)
        self._gate("spec_draft", decode_rids)
        t0 = time.perf_counter()
        t_prof = self._profile_t0()
        drafts = self._spec_draft_exe()(
            self.params, self._spec_dlayers, c.k, c.v, c.k_scale,
            c.v_scale, c.table, c.lengths, self.cur_tok, active)
        self._profile_commit(t_prof, drafts, mode="spec_draft",
                             bucket=self.spec_k, units=0,
                             rids=decode_rids)
        draft_s = time.perf_counter() - t0
        self._record_tick(
            "spec_verify", rids=decode_rids, k=self.spec_k,
            compile_hit=self._spec_key("verify") in self._spec_cache)
        self._gate("spec_verify", decode_rids)
        t1 = time.perf_counter()
        t_prof = self._profile_t0()
        (pk, pv, ks, vs, lengths, last, budget, active2, out, n_emit,
         n_acc) = self._spec_verify_exe()(
            self.params, c.k, c.v, c.k_scale, c.v_scale, c.table,
            c.lengths, self.cur_tok, drafts, active, budget, stop,
            self._spec_ok_dev)
        dev_s = self._profile_commit(
            t_prof, (pk, out, n_emit), mode="spec_verify",
            bucket=self.spec_k, units=0, rids=decode_rids)
        # one host sync serves tokens, counts AND acceptance — and,
        # dispatch being async, surfaces any device-side failure HERE,
        # before the batcher state commits below
        out, n_emit, n_acc = jax.device_get((out, n_emit, n_acc))  # ptlint: disable=SYNC001 — single per-step sync, token + acceptance readbacks coalesced
        verify_s = time.perf_counter() - t1
        self.cache = PagedKVCache(pk, pv, c.table, lengths, ks, vs)
        self.cur_tok = last
        self._dev_state = (active2, budget, stop)
        spec_slots = sum(1 for s in range(self.B) if self.active[s]
                         and self.slot_req[s] not in self._no_spec)
        self.spec.record_step(drafted=self.spec_k * spec_slots,
                              accepted=int(n_acc.sum()),
                              emitted=int(n_emit.sum()),
                              slots=len(decode_rids),
                              depths=[int(n_acc[s])
                                      for s in range(self.B)
                                      if self.active[s]
                                      and self.slot_req[s]
                                      not in self._no_spec])
        if self._trace is not None:
            self._trace.span("spec_draft", dur=draft_s, k=self.spec_k,
                             slots=len(decode_rids),
                             replica_id=self.replica_id)
            for s in range(self.B):
                if self.active[s]:
                    extra = {} if dev_s is None \
                        else {"device_dur": round(dev_s, 6)}
                    self._trace_emit(
                        self.slot_req[s], "spec_verify", dur=verify_s,
                        accepted=int(n_acc[s]), emitted=int(n_emit[s]),
                        k=self.spec_k, **extra)
        return out, n_emit

    def _spec_any(self) -> bool:
        """True when at least one ACTIVE slot participates in the
        spec pipeline — with every active request opted out (the
        quarantine fallback), the plain chunk step is strictly better
        (one device call, `chunk` tokens per slot) than a vacuous
        draft+verify pair emitting one."""
        return any(self.active[s] and self.slot_req[s] not in
                   self._no_spec for s in range(self.B))

    def _emit_spec(self, decoding, out, n_emit) -> None:
        """Deliver one spec tick's emitted tokens (the host mirror of
        the device stopping rule) and retire finished slots."""
        for slot in decoding:
            rid = self.slot_req[slot]
            for j in range(int(n_emit[slot])):
                self.outputs[rid].append(int(out[slot, j]))
                self.budget[slot] -= 1
            o = self.outputs[rid]
            done = (self.budget[slot] <= 0
                    or (self.eos is not None and o
                        and o[-1] == self.eos)
                    or (self.stop[slot] >= 0 and o
                        and o[-1] == self.stop[slot]))
            if done:
                self._retire(slot)

    def step(self):
        """Admit what fits, then run ONE device chunk — fused with up to
        one admission-prefill unit when slots are decoding, plain decode
        otherwise.

        The serving layer's granularity: returns (emitted, finished) —
        `emitted` maps rid -> tokens newly generated since the last
        step() (the prefill's first token included), `finished` lists
        rids that completed this step (their blocks are already back in
        the pool). A step with nothing in flight is a cheap no-op."""
        self._admit()
        if any(self.active):
            # slots committed by a fused admission AFTER the device call
            # must not read this chunk's token rows — they were inactive
            # (masked) rows during the call itself
            decoding = [s for s in range(self.B) if self.active[s]]
            if self.speculative and not self._fuse_now() \
                    and self._spec_any():
                # speculative tick: draft + verify emit up to spec_k+1
                # tokens per slot. Admission pressure still rides the
                # PR 5 fused path (the `_fuse_now` tick above runs a
                # plain chunk + piggybacked prefill — greedy tokens
                # are schedule-invariant, so mixing the two step kinds
                # never changes output)
                out, n_emit = self._step_spec()
                self._emit_spec(decoding, out, n_emit)
                self._admit()
                return self._drain_emitted()
            if self._fuse_now():
                toks = self._step_fused()
            else:
                decode_rids = [self.slot_req[s] for s in decoding]
                self._record_tick(
                    "decode", rids=decode_rids,
                    compile_hit=(self.chunk, self.attention_impl)
                    + self._skey + self._qkey + self._mkey
                    in self._chunk_cache)
                self._gate("decode", decode_rids)
                if self._dev_state is None:
                    self._dev_state = self._upload_slot_state()
                active, budget, stop = self._dev_state
                t_prof = self._profile_t0()
                (self.cache, self.cur_tok, lengths, budget, active,
                 toks) = self._chunk_exe()(
                    self.params, self.cache, self.cur_tok, active,
                    self.cache.lengths, budget, stop)
                self._profile_commit(
                    t_prof, (self.cache.k, self.cur_tok, toks),
                    mode="decode", bucket=self.chunk, units=0,
                    rids=decode_rids)
                self.cache = self.cache._replace(lengths=lengths)
                # steady state: the chunk's own outputs are next chunk's
                # inputs; _retire/_commit null this when the host diverges
                self._dev_state = (active, budget, stop)
                # one host sync per decode chunk — the per-token loop
                # below reads this numpy copy, never the device
                toks = np.asarray(toks)  # ptlint: disable=SYNC001 — single per-chunk sync, hoisted out of the per-token loop
            for slot in decoding:
                rid = self.slot_req[slot]
                for j in range(self.chunk):
                    if self.budget[slot] <= 0:
                        break
                    t = int(toks[slot, j])
                    self.outputs[rid].append(t)
                    self.budget[slot] -= 1
                    if ((self.eos is not None and t == self.eos)
                            or t == self.stop[slot]):
                        break
                out = self.outputs[rid]
                done = (self.budget[slot] <= 0 or
                        (self.eos is not None and out and
                         out[-1] == self.eos) or
                        (self.stop[slot] >= 0 and out and
                         out[-1] == self.stop[slot]))
                if done:
                    self._retire(slot)
            self._admit()
        return self._drain_emitted()

    def _drain_emitted(self):
        """The step() return contract: (emitted rid -> new tokens,
        finished rids) off the delivery bookkeeping — shared by the
        chunk, fused and speculative step kinds."""
        emitted: Dict[int, List[int]] = {}
        for rid, n in list(self._delivered.items()):
            out = self.outputs.get(rid)
            if out is not None and len(out) > n:
                emitted[rid] = out[n:]
                self._delivered[rid] = len(out)
        finished, self._just_finished = self._just_finished, []
        for rid in finished:
            self._delivered.pop(rid, None)
        return emitted, finished

    def run(self) -> Dict[int, List[int]]:
        """Drain the queue and all in-flight requests (greedy decode)."""
        while True:
            self.step()
            if not (any(self.active) or self.queue or self._pending):
                break
        return self.outputs

