"""ERNIE-family encoder — the BASELINE 'ERNIE-3.0 finetune (DP)' workload.

Reference analog: PaddleNLP's ERNIE/BERT encoder stack (out-of-repo domain
suite, SURVEY.md §1 Lx row; upstream-canonical, unverified — SURVEY.md §0):
a bidirectional transformer encoder with learned position + token-type
embeddings, post-LN blocks, a pooler, and MLM/classification heads, trained
under fleet data parallelism.

TPU-native design (mirrors nlp/llama.py): pure-functional params pytree with
layers stacked on a leading [L] dim and scanned; `param_specs` carries the
TP (mp) + ZeRO-3 (sharding) PartitionSpec table; DP finetune is just batch
sharding over (dp, sharding). bf16 compute, f32 params/softmax.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 4
    layer_norm_eps: float = 1e-12
    num_labels: int = 2                 # classification head width
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    # unroll for the layer scan (True = fully unrolled). Unrolling turns
    # the backward scan's per-layer grad stacking (dynamic-update-slice
    # into the [L, ...] grad tensors — ~24 ms/step in the r5 xplane) into
    # static writes XLA simplifies; measured +0.8pt MFU on the bench at
    # L=12. Keep the default scan (1) for deep models where compile time
    # and code size dominate.
    scan_unroll: Any = 1

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def tiny(**over) -> "ErnieConfig":
        base = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=64, type_vocab_size=2)
        base.update(over)
        return ErnieConfig(**base)

    @staticmethod
    def ernie3_base(**over) -> "ErnieConfig":
        base = dict(vocab_size=40000, hidden_size=768, num_hidden_layers=12,
                    num_attention_heads=12, intermediate_size=3072)
        base.update(over)
        return ErnieConfig(**base)


def init_params(key: jax.Array, cfg: ErnieConfig) -> Dict[str, Any]:
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    pd = cfg.param_dtype
    ks = jax.random.split(key, 12)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(pd)

    return {
        "word_embeddings": norm(ks[0], (cfg.vocab_size, D)),
        "position_embeddings": norm(ks[1], (cfg.max_position_embeddings, D)),
        "token_type_embeddings": norm(ks[2], (cfg.type_vocab_size, D)),
        "embed_norm_scale": jnp.ones((D,), pd),
        "embed_norm_bias": jnp.zeros((D,), pd),
        "layers": {
            # separate q/k/v projections (upstream ERNIE/BERT keep
            # q_proj/k_proj/v_proj distinct in nn.MultiHeadAttention) —
            # also what lets TP's 'mp' sharding propagate through the
            # [D, D] -> [D, H, hd] reshape of the einsum-form attention
            # (a fused [D, 3D] merges (3, H, hd), whose leading factor 3
            # is indivisible by mp, so GSPMD propagation gave up)
            "q_w": norm(ks[3], (L, D, D)),
            "q_b": jnp.zeros((L, D), pd),
            "k_w": norm(ks[10], (L, D, D)),
            "k_b": jnp.zeros((L, D), pd),
            "v_w": norm(ks[11], (L, D, D)),
            "v_b": jnp.zeros((L, D), pd),
            "out_w": norm(ks[4], (L, D, D)),
            "out_b": jnp.zeros((L, D), pd),
            "attn_norm_scale": jnp.ones((L, D), pd),
            "attn_norm_bias": jnp.zeros((L, D), pd),
            "ffn_in_w": norm(ks[5], (L, D, F)),
            "ffn_in_b": jnp.zeros((L, F), pd),
            "ffn_out_w": norm(ks[6], (L, F, D)),
            "ffn_out_b": jnp.zeros((L, D), pd),
            "ffn_norm_scale": jnp.ones((L, D), pd),
            "ffn_norm_bias": jnp.zeros((L, D), pd),
        },
        "pooler_w": norm(ks[7], (D, D)),
        "pooler_b": jnp.zeros((D,), pd),
        "classifier_w": norm(ks[8], (D, cfg.num_labels)),
        "classifier_b": jnp.zeros((cfg.num_labels,), pd),
        "mlm_transform_w": norm(ks[9], (D, D)),
        "mlm_transform_b": jnp.zeros((D,), pd),
        "mlm_norm_scale": jnp.ones((D,), pd),
        "mlm_norm_bias": jnp.zeros((D,), pd),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), pd),
    }


def param_specs(cfg: ErnieConfig) -> Dict[str, Any]:
    """TP (mp) + ZeRO-3 (sharding) table; the DP finetune config runs with
    mp=1 and this degenerates to pure FSDP (SURVEY.md §2.3 DP/sharding)."""
    return {
        "word_embeddings": P("mp", "sharding"),
        "position_embeddings": P(None, "sharding"),
        "token_type_embeddings": P(None, "sharding"),
        "embed_norm_scale": P(None),
        "embed_norm_bias": P(None),
        "layers": {
            "q_w": P(None, "sharding", "mp"),
            "q_b": P(None, "mp"),
            "k_w": P(None, "sharding", "mp"),
            "k_b": P(None, "mp"),
            "v_w": P(None, "sharding", "mp"),
            "v_b": P(None, "mp"),
            "out_w": P(None, "mp", "sharding"),
            "out_b": P(None, None),
            "attn_norm_scale": P(None, None),
            "attn_norm_bias": P(None, None),
            "ffn_in_w": P(None, "sharding", "mp"),
            "ffn_in_b": P(None, "mp"),
            "ffn_out_w": P(None, "mp", "sharding"),
            "ffn_out_b": P(None, None),
            "ffn_norm_scale": P(None, None),
            "ffn_norm_bias": P(None, None),
        },
        "pooler_w": P("sharding", "mp"),
        "pooler_b": P("mp"),
        "classifier_w": P("sharding", None),
        "classifier_b": P(None),
        "mlm_transform_w": P("sharding", "mp"),
        "mlm_transform_b": P("mp"),
        "mlm_norm_scale": P(None),
        "mlm_norm_bias": P(None),
        "mlm_bias": P("mp"),
    }


def batch_spec() -> P:
    return P(("dp", "sharding"), None)


def _layer_norm(x, scale, bias, eps):
    # plain jnp on purpose, re-measured in round 5: the Pallas
    # layer_norm_train kernel was +0.07pt MFU on the bench (noise) even
    # after flash removed the S^2 score traffic, and this module's API
    # has no mesh handle to gate the GSPMD-opaque pallas path the way
    # llama/moe do — jnp keeps TP/FSDP ERNIE runs partitionable.
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32) +
            bias.astype(jnp.float32)).astype(x.dtype)


def _encoder_layer(x, lp, cfg: ErnieConfig, mask):
    # attention via the non-causal Pallas flash kernel (key-padding mask
    # rides into the kernel; kernels/flash_attention.py). The r4 bench ran
    # this layer's naive [B,H,S,S] f32 score path — profiled at ~150 of
    # 316 ms/step (VERDICT r4 weak 2); flash removes the S^2 HBM traffic.
    # On CPU both entries fall back to exact mha_ref.
    from ..kernels import flash_attention as fa
    dt = cfg.dtype
    B, S, D = x.shape
    H, hd = cfg.num_attention_heads, cfg.head_dim
    # einsum-form attention, head-major throughout: q/k/v land [B,H,S,hd]
    # straight out of the projection dots and flash runs layout='bhsd', so
    # the [B,S,H,hd]<->[B,H,S,hd] relayouts around the custom-call (the
    # r5 xplane's ~30ms of bf16[64,12,512,64] copies) never materialize —
    # the transposes ride inside dot_general's operand/result layouts.
    q, k, v = [jnp.einsum("bsd,dhe->bhse", x,
                          lp[w].astype(dt).reshape(D, H, hd)) +
               lp[b].astype(dt).reshape(H, hd)[None, :, None, :]
               for w, b in (("q_w", "q_b"), ("k_w", "k_b"), ("v_w", "v_b"))]
    if mask is None and not fa.block_aligned(S):
        # unaligned seq: an all-ones key mask keeps flash eligible (the
        # masked kernel pads keys and hides them via the mask; the
        # unmasked non-causal gate would fall back to O(S^2) exact)
        mask = jnp.ones((B, S), bool)
    if mask is None:
        ctx = fa.flash_attention_fwd(q, k, v, False, None, "bhsd")
    else:
        ctx = fa.flash_attention_masked(q, k, v, mask, None, "bhsd")
    attn_out = jnp.einsum("bhse,hed->bsd", ctx,
                          lp["out_w"].astype(dt).reshape(H, hd, D)) + \
        lp["out_b"].astype(dt)
    x = _layer_norm(x + attn_out, lp["attn_norm_scale"],
                    lp["attn_norm_bias"], cfg.layer_norm_eps)
    h = jax.nn.gelu(x @ lp["ffn_in_w"].astype(dt) +
                    lp["ffn_in_b"].astype(dt), approximate=True)
    h = h @ lp["ffn_out_w"].astype(dt) + lp["ffn_out_b"].astype(dt)
    return _layer_norm(x + h, lp["ffn_norm_scale"], lp["ffn_norm_bias"],
                       cfg.layer_norm_eps)


def encode(params, input_ids, token_type_ids=None, attention_mask=None,
           cfg: ErnieConfig = None):
    """→ sequence output [B, S, D] (compute dtype)."""
    dt = cfg.dtype
    B, S = input_ids.shape
    x = params["word_embeddings"][input_ids] + \
        params["position_embeddings"][jnp.arange(S)][None] + \
        params["token_type_embeddings"][
            token_type_ids if token_type_ids is not None
            else jnp.zeros_like(input_ids)]
    x = _layer_norm(x.astype(dt), params["embed_norm_scale"],
                    params["embed_norm_bias"], cfg.layer_norm_eps)

    def body(h, lp):
        fn = _encoder_layer
        if cfg.remat:
            fn = jax.checkpoint(fn, static_argnums=(2,))
        return fn(h, lp, cfg, attention_mask), None

    x, _ = jax.lax.scan(body, x, params["layers"], unroll=cfg.scan_unroll)
    return x


def forward(params, input_ids, token_type_ids=None, attention_mask=None,
            cfg: ErnieConfig = None):
    """→ (sequence_output [B,S,D], pooled_output [B,D]) like the reference's
    ErnieModel.forward."""
    seq = encode(params, input_ids, token_type_ids, attention_mask, cfg)
    pooled = jnp.tanh(seq[:, 0] @ params["pooler_w"].astype(cfg.dtype) +
                      params["pooler_b"].astype(cfg.dtype))
    return seq, pooled


def cls_logits(params, pooled, cfg: ErnieConfig):
    return (pooled.astype(jnp.float32) @
            params["classifier_w"].astype(jnp.float32) +
            params["classifier_b"].astype(jnp.float32))


def mlm_logits(params, seq, cfg: ErnieConfig):
    h = jax.nn.gelu(seq @ params["mlm_transform_w"].astype(cfg.dtype) +
                    params["mlm_transform_b"].astype(cfg.dtype),
                    approximate=True)
    h = _layer_norm(h, params["mlm_norm_scale"], params["mlm_norm_bias"],
                    cfg.layer_norm_eps)
    # decoder tied to word embeddings (reference ties MLM head weights)
    return (h.astype(jnp.float32) @
            params["word_embeddings"].T.astype(jnp.float32) +
            params["mlm_bias"].astype(jnp.float32))


def finetune_loss(params, input_ids, labels, cfg: ErnieConfig,
                  token_type_ids=None, attention_mask=None):
    """Sequence-classification CE (the BASELINE finetune objective)."""
    _, pooled = forward(params, input_ids, token_type_ids, attention_mask,
                        cfg)
    logits = cls_logits(params, pooled, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))


def mlm_loss(params, input_ids, mlm_labels, cfg: ErnieConfig,
             token_type_ids=None, attention_mask=None, ignore_index=-100):
    seq = encode(params, input_ids, token_type_ids, attention_mask, cfg)
    logits = mlm_logits(params, seq, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    mask = mlm_labels != ignore_index
    safe = jnp.where(mask, mlm_labels, 0)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)


def num_params(cfg: ErnieConfig) -> int:
    D, F, L, V = (cfg.hidden_size, cfg.intermediate_size,
                  cfg.num_hidden_layers, cfg.vocab_size)
    per_layer = 3 * D * D + 3 * D + D * D + D + 2 * D * F + F + D + 4 * D
    emb = V * D + cfg.max_position_embeddings * D + cfg.type_vocab_size * D
    return emb + L * per_layer + 2 * D + (D * D + D) + \
        (D * cfg.num_labels + cfg.num_labels) + (D * D + D + 2 * D + V)


def flops_per_token(cfg: ErnieConfig, seq_len: int) -> float:
    """Approx. train FLOPs/token (fwd+bwd = 6x fwd MACs): encoder qkvo +
    ffn matmuls + BIDIRECTIONAL attention (every token attends all seq_len
    keys — no causal halving, unlike llama.flops_per_token)."""
    D, F, H = cfg.hidden_size, cfg.intermediate_size, cfg.num_attention_heads
    matmul = 4 * D * D + 2 * D * F
    attn = 2 * H * cfg.head_dim * seq_len
    return 6.0 * cfg.num_hidden_layers * (matmul + attn)
