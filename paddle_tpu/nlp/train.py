"""Sharded training step for the flagship models.

Reference analog: the fleet hybrid-parallel train loop —
`fleet.distributed_model` + `distributed_optimizer` + per-strategy wrappers
(SURVEY.md §3.2, upstream-canonical, unverified §0). TPU-native: ONE jitted
train step whose in/out shardings carry the whole strategy; XLA inserts every
collective (grad psum over dp, FSDP all-gathers over 'sharding', TP
collectives over 'mp') — the reference's reducer/GroupSharded/mp_ops code
has no runtime equivalent here by design.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import llama


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(learning_rate=3e-4, weight_decay=0.1, b1=0.9, b2=0.95,
                   grad_clip=1.0, warmup_steps=0, total_steps=10000,
                   state_quant: Optional[str] = None):
    """AdamW + cosine schedule + global-norm clip — the reference's Llama
    recipe optimizer (paddle.optimizer.AdamW + LinearWarmup/Cosine).

    state_quant="8bit" stores the Adam moments 8-bit blockwise — float8
    codes + per-block scales (optimizer.quant_state; NOT linear int8,
    which underflows) — ~2 bytes/param of state instead of 8, the
    single-chip flagship-bench mode; None keeps f32 moments (multi-chip
    shards those over 'sharding' instead). "int8" is accepted as an
    alias for the storage-width reading of the name."""
    if warmup_steps:
        sched = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, warmup_steps, total_steps)
    else:
        sched = learning_rate
    if state_quant is None:
        adam = optax.adamw(sched, b1=b1, b2=b2, weight_decay=weight_decay)
    elif state_quant in ("8bit", "int8"):
        # the clip streams through the chunked 8-bit update (no second
        # grad tree — the single-chip 2B config OOMs with the optax clip);
        # on TPU the train step takes the fused one-pass Pallas apply
        # (decode+adam+requant+param update in ~10 bytes/param of HBM
        # traffic instead of the chain's ~5 full-tree passes)
        from ..optimizer.quant_state import adamw_q_fused
        return adamw_q_fused(sched, b1=b1, b2=b2,
                             weight_decay=weight_decay,
                             clip_norm=grad_clip or None)
    else:
        raise ValueError(f"unknown state_quant {state_quant!r}")
    tx = optax.chain(
        optax.clip_by_global_norm(grad_clip) if grad_clip else optax.identity(),
        adam,
    )
    return tx


def state_specs(cfg, tx, pp: bool = False, model=llama) -> TrainState:
    """PartitionSpec tree for the full TrainState: optimizer moments inherit
    each param's spec (= ZeRO: opt state sharded exactly like params).
    `model` is the model module (llama or moe) — both expose init_params/
    param_specs/loss_fn with the same signatures."""
    pspecs = model.param_specs(cfg, pp=pp)
    params_shape = jax.eval_shape(
        functools.partial(model.init_params, cfg=cfg), jax.random.key(0))
    opt_state_shape = jax.eval_shape(tx.init, params_shape)
    opt_specs = _opt_specs_like(opt_state_shape, params_shape, pspecs)
    return TrainState(step=P(), params=pspecs, opt_state=opt_specs)


def _opt_specs_like(opt_state_shape, params_shape, pspecs):
    """Map an optax state pytree to specs: any subtree that is structurally
    identical to the param tree gets the param specs; other leaves P()."""
    params_treedef = jax.tree.structure(params_shape)

    def rec(node):
        try:
            if jax.tree.structure(node) == params_treedef:
                return pspecs
        # ptlint: disable=EXC001 — structure() on arbitrary optax state
        # leaves raises type-dependent errors; "not param-shaped" is the
        # answer, recursion below handles the node
        except Exception:
            pass
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*[rec(c) for c in node])
        if isinstance(node, tuple):
            return tuple(rec(c) for c in node)
        if isinstance(node, list):
            return [rec(c) for c in node]
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return P()

    return rec(opt_state_shape)


def _use_pp(mesh: Optional[Mesh]) -> bool:
    return (mesh is not None and "pp" in mesh.axis_names
            and mesh.shape["pp"] > 1)


def init_state(key, cfg, tx, mesh: Optional[Mesh] = None, model=llama):
    """Initialize params + opt state, jitted with out_shardings so big models
    materialize directly sharded (never replicated on one chip)."""
    def init():
        params = model.init_params(key, cfg)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=tx.init(params))

    if mesh is None:
        return init()
    pp = _use_pp(mesh) and hasattr(model, "forward_pp")
    specs = state_specs(cfg, tx, pp=pp, model=model)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    return jax.jit(init, out_shardings=shardings)()


def make_train_step(cfg, tx, mesh: Optional[Mesh] = None,
                    donate: bool = True,
                    num_microbatches: Optional[int] = None,
                    grad_accum_steps: int = 1,
                    pp_schedule: str = "1f1b",
                    virtual_pp_degree: int = 2,
                    model=llama) -> Callable:
    """Build the jitted train step. With a mesh: full GSPMD shardings on
    state and batch; without: plain jit (single device). A mesh with pp > 1
    runs the decoder through a compiled pipeline schedule —
    `num_microbatches` (default 2·pp) microbatches per step (llama AND moe
    both pipeline via their forward_pp). pp_schedule picks the compiled
    schedule (reference: PipelineParallel's 1F1B / interleaved modes,
    SURVEY.md §3.3): "1f1b" (default) runs the fused one_f_one_b
    forward+backward with O(pp) activation residency; "gpipe" runs
    forward_pp under jax.grad (scan transpose, O(num_microbatches)
    residency) and is the automatic fallback for models without a
    loss_and_grad_pp; "interleaved" runs the interleaved/virtual-pp 1F1B
    (virtual_pp_degree chunks per device — bubble shrinks by that factor,
    O(v·pp) residency) when the model has loss_and_grad_pp, else the
    circular virtual-pp GPipe under jax.grad.

    grad_accum_steps > 1 splits the batch axis into that many chunks and
    accumulates grads through one lax.scan before the optimizer update —
    the reference's gradient-merge / accumulate_steps (fleet
    DistributedStrategy), compiled instead of host-looped. Activation
    memory drops by the accumulation factor; numerics match the full batch
    up to bf16 forward rounding (chunked reductions associate differently).
    Chunks interleave rows (strided) so each chunk stays spread across the
    dp/sharding batch shards."""
    pp = _use_pp(mesh) and hasattr(model, "forward_pp")
    mb = (num_microbatches or 2 * mesh.shape["pp"]) if pp else None
    if pp_schedule not in ("1f1b", "gpipe", "interleaved"):
        raise ValueError(f"unknown pp_schedule {pp_schedule!r}")
    use_1f1b = (pp and pp_schedule in ("1f1b", "interleaved")
                and hasattr(model, "loss_and_grad_pp"))
    pp_virtual = virtual_pp_degree if (
        pp and pp_schedule == "interleaved") else 1
    if grad_accum_steps < 1:
        raise ValueError(
            f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    if grad_accum_steps > 1 and pp:
        raise ValueError(
            "grad_accum_steps composes with num_microbatches inside the pp "
            "schedule — use num_microbatches when pp > 1")

    def step_fn(state: TrainState, tokens):
        if pp:
            if pp_virtual > 1:
                lfn = lambda p, t: model.loss_fn(  # noqa: E731
                    p, t, cfg, mesh, mb, pp_virtual)
            else:
                lfn = lambda p, t: model.loss_fn(p, t, cfg, mesh, mb)  # noqa: E731
        else:
            lfn = lambda p, t: model.loss_fn(p, t, cfg, mesh)  # noqa: E731
        if grad_accum_steps > 1:
            b = tokens.shape[0]
            if b % grad_accum_steps:
                raise ValueError(
                    f"batch {b} not divisible by grad_accum_steps "
                    f"{grad_accum_steps}")
            # strided (row-interleaved) chunks: contiguous blocks would
            # concentrate each chunk onto one dp/sharding shard and force a
            # reshard per scan iteration
            chunks = jnp.swapaxes(
                tokens.reshape((b // grad_accum_steps, grad_accum_steps)
                               + tokens.shape[1:]), 0, 1)

            def micro(carry, mtoks):
                gsum, lsum = carry
                l, g = jax.value_and_grad(lfn)(state.params, mtoks)
                return (jax.tree.map(jnp.add, gsum, g), lsum + l), None

            init = (jax.tree.map(jnp.zeros_like, state.params),
                    jnp.zeros((), jnp.float32))
            (gsum, lsum), _ = jax.lax.scan(micro, init, chunks)
            grads = jax.tree.map(lambda g: g / grad_accum_steps, gsum)
            loss = lsum / grad_accum_steps
        elif use_1f1b:
            loss, grads = model.loss_and_grad_pp(
                state.params, tokens, cfg, mesh, mb, pp_virtual)
        else:
            loss, grads = jax.value_and_grad(lfn)(state.params, tokens)
        if mesh is None and hasattr(tx, "apply_fused"):
            # single chip: one-pass Pallas update (params+moments in one
            # pipelined stream); under a mesh the pure-jnp update tree
            # stays so GSPMD can shard it
            new_params, new_opt = tx.apply_fused(
                grads, state.opt_state, state.params)
        else:
            # ptlint: disable=TRACE001 — optax GradientTransformation.
            # update is pure: it RETURNS (updates, new_state), mutating
            # nothing (the name collides with dict.update)
            updates, new_opt = tx.update(grads, state.opt_state,
                                         state.params)
            new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss,
                   "grad_norm": optax.global_norm(grads),
                   "step": state.step}
        return TrainState(state.step + 1, new_params, new_opt), metrics

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0,) if donate else ())

    specs = state_specs(cfg, tx, pp=pp, model=model)
    state_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))
    batch_sh = NamedSharding(
        mesh, getattr(model, "batch_spec", llama.batch_spec)())
    metric_sh = {"loss": NamedSharding(mesh, P()),
                 "grad_norm": NamedSharding(mesh, P()),
                 "step": NamedSharding(mesh, P())}
    return jax.jit(step_fn,
                   in_shardings=(state_sh, batch_sh),
                   out_shardings=(state_sh, metric_sh),
                   donate_argnums=(0,) if donate else ())
