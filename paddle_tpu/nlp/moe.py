"""Mixture-of-Experts: gating, capacity dispatch, expert parallelism, and
the flagship MoE transformer (DeepSeekMoE/Qwen2-MoE-style — BASELINE
config 4).

Reference analog: python/paddle/incubate/distributed/models/moe/
(moe_layer.py with gshard/switch/naive gates, capacity + all_to_all dispatch
over the moe_group, fused dispatch CUDA kernels) and the PaddleNLP
DeepSeekMoE recipes — upstream-canonical, unverified, SURVEY.md §0, §2.3 EP
row.

TPU-native design (SURVEY.md §7 M7): GShard-style STATIC-SHAPE dispatch —
top-k gating builds [T, E, C] one-hot dispatch/combine tensors (cumsum
position assignment, capacity-dropped tokens fall through the residual);
dispatch and combine are einsums, so under GSPMD with experts sharded
P('ep', ...) XLA inserts the all_to_all the reference hand-codes. The whole
MoE block stays differentiable jnp — no host-side routing, no ragged shapes.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..kernels.rms_norm import rms_norm_ref, rms_norm_train
from ..kernels.rope import rope_freqs
from . import llama as _llama


def gshard_capacity(tokens: int, k: int, num_experts: int,
                    factor: float) -> int:
    """GShard expert capacity: ceil-ish share of k·T routed slots per
    expert, scaled by the capacity factor (single source of the rounding
    rule for MoeConfig and the incubate MoELayer facade)."""
    per = tokens * k / num_experts
    return max(int(per * factor + 0.5), 1)


def top_k_routing(gate_logits: jax.Array, k: int, capacity: int,
                  renormalize: bool = True):
    """GShard top-k gating with capacity, INDEX form.

    gate_logits: [T, E] (f32). Returns (eidx [T,k] i32, slot [T,k] i32,
    probs [T,k] f32, valid [T,k] bool, inv [E,C] i32, aux): token t's j-th
    choice goes to expert eidx[t,j] at capacity slot slot[t,j] with gate
    weight probs[t,j], dropped when not valid; inv is the inverse map
    (which token fills slot [e,c]; -1 = empty). Everything downstream is
    gathers over these indices — nothing materializes [T,E,C] (the round-1
    einsum dispatch; VERDICT item 4: memory scaled with E*C).
    """
    T, E = gate_logits.shape
    probs_full = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    # iterative top-k: mask out chosen experts each round
    masked = probs_full
    sel_idx = []            # k × [T] chosen expert
    sel_masks = []          # k × [T, E] one-hot
    sel_probs = []          # k × [T]
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        sel_idx.append(idx.astype(jnp.int32))
        sel_masks.append(onehot)
        sel_probs.append(jnp.sum(probs_full * onehot, axis=-1))
        masked = masked * (1.0 - onehot)

    if renormalize:
        denom = sum(sel_probs)
        sel_probs = [p / jnp.maximum(denom, 1e-9) for p in sel_probs]

    # capacity slots: cumulative position of each token within its expert,
    # later-k choices stack after earlier-k occupancy (GShard ordering)
    slots, valids = [], []
    prior_count = jnp.zeros((E,), jnp.float32)
    for mask in sel_masks:
        pos = jnp.cumsum(mask, axis=0) - 1.0 + prior_count[None, :]
        prior_count = prior_count + jnp.sum(mask, axis=0)
        in_cap = (pos < capacity) & (mask > 0)
        slots.append(jnp.sum(pos * mask, axis=-1).astype(jnp.int32))
        valids.append(jnp.any(in_cap, axis=-1))

    eidx = jnp.stack(sel_idx, axis=1)                    # [T, k]
    slot = jnp.stack(slots, axis=1)                      # [T, k]
    probs = jnp.stack(sel_probs, axis=1)                 # [T, k]
    valid = jnp.stack(valids, axis=1)                    # [T, k]

    # inverse map: token filling each (e, c) slot — scatter token ids into
    # a flat [E*C] table (+1 dump slot for dropped/invalid entries)
    flat = eidx * capacity + slot                        # [T, k]
    flat = jnp.where(valid, flat, E * capacity)
    inv = jnp.full((E * capacity + 1,), -1, jnp.int32)
    tok = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[:, None], flat.shape)
    inv = inv.at[flat.reshape(-1)].set(tok.reshape(-1), mode="drop")
    inv = inv[:-1].reshape(E, capacity)

    # Switch load-balance loss: E * Σ_e fraction_tokens_e · mean_prob_e
    # (fraction from the FIRST choice, the standard formulation)
    frac = jnp.mean(sel_masks[0], axis=0)
    mean_p = jnp.mean(probs_full, axis=0)
    aux = {
        "load_balance_loss": E * jnp.sum(frac * mean_p),
        "router_z_loss": jnp.mean(
            jax.scipy.special.logsumexp(gate_logits, axis=-1) ** 2),
    }
    return eidx, slot, probs, valid, inv, aux


def top_k_gating(gate_logits: jax.Array, k: int, capacity: int,
                 renormalize: bool = True
                 ) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """GShard top-k gating, ONE-HOT form (the incubate MoELayer facade and
    tests): [T,E,C] dispatch/combine built from top_k_routing's indices —
    single-sourcing the assignment rule. Prefer the index form for anything
    large; this materializes the O(T*E*C) tensors."""
    T, E = gate_logits.shape
    eidx, slot, probs, valid, _, aux = top_k_routing(
        gate_logits, k, capacity, renormalize)
    # accumulate per choice j: peak memory stays one [T,E,C] (the eager
    # incubate facade runs this op-by-op — a [T,k,E,C] intermediate would
    # k-fold the old peak)
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    for j in range(k):
        oh = (jax.nn.one_hot(eidx[:, j], E, dtype=jnp.float32)[..., None]
              * jax.nn.one_hot(slot[:, j], capacity, dtype=jnp.float32)[:, None]
              * valid[:, j, None, None].astype(jnp.float32))
        dispatch = dispatch + oh
        combine = combine + oh * probs[:, j, None, None]
    return dispatch, combine, aux


@dataclasses.dataclass
class MoeConfig:
    """Flagship MoE transformer (Qwen2-MoE/DeepSeekMoE shape: routed experts
    + optional always-on shared expert)."""
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632       # dense (shared) FFN width
    moe_intermediate_size: int = 1408   # per-expert FFN width
    num_experts: int = 8
    num_experts_per_tok: int = 2
    num_shared_experts: int = 1         # 0 disables the shared expert
    capacity_factor: float = 1.25
    num_hidden_layers: int = 4
    num_attention_heads: int = 16
    num_key_value_heads: int = 8
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    router_aux_loss_coef: float = 0.01
    router_z_loss_coef: float = 0.001
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attn_impl: str = "flash"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    def capacity(self, tokens: int) -> int:
        return gshard_capacity(tokens, self.num_experts_per_tok,
                               self.num_experts, self.capacity_factor)

    @staticmethod
    def tiny(**over) -> "MoeConfig":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    moe_intermediate_size=32, num_experts=4,
                    num_experts_per_tok=2, num_hidden_layers=2,
                    num_attention_heads=4, num_key_value_heads=2,
                    max_position_embeddings=128)
        base.update(over)
        return MoeConfig(**base)

    @staticmethod
    def qwen2_moe_a14b(**over) -> "MoeConfig":
        """Qwen2-57B-A14B-shaped config (public card numbers)."""
        base = dict(vocab_size=151936, hidden_size=3584,
                    intermediate_size=18944, moe_intermediate_size=2560,
                    num_experts=64, num_experts_per_tok=8,
                    num_shared_experts=1, num_hidden_layers=28,
                    num_attention_heads=28, num_key_value_heads=4,
                    max_position_embeddings=32768, rope_theta=1000000.0)
        base.update(over)
        return MoeConfig(**base)

    @staticmethod
    def deepseek_moe_16b(**over) -> "MoeConfig":
        """DeepSeekMoE-16B-shaped config (public card numbers)."""
        base = dict(vocab_size=102400, hidden_size=2048,
                    intermediate_size=10944, moe_intermediate_size=1408,
                    num_experts=64, num_experts_per_tok=6,
                    num_shared_experts=2, num_hidden_layers=28,
                    num_attention_heads=16, num_key_value_heads=16,
                    max_position_embeddings=4096)
        base.update(over)
        return MoeConfig(**base)


def _llama_cfg(cfg: MoeConfig) -> _llama.LlamaConfig:
    """Attention reuses the llama block implementation."""
    return _llama.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        num_key_value_heads=cfg.num_key_value_heads,
        max_position_embeddings=cfg.max_position_embeddings,
        rms_norm_eps=cfg.rms_norm_eps, rope_theta=cfg.rope_theta,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype, remat=cfg.remat,
        attn_impl=cfg.attn_impl, use_flash=True)


def init_params(key: jax.Array, cfg: MoeConfig) -> Dict[str, Any]:
    """Parameter pytree; layers stacked [L], experts stacked [E]."""
    D, V, L = cfg.hidden_size, cfg.vocab_size, cfg.num_hidden_layers
    E, Fm = cfg.num_experts, cfg.moe_intermediate_size
    H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    pd = cfg.param_dtype
    ks = jax.random.split(key, 12)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(pd)

    layers = {
        "input_layernorm": jnp.ones((L, D), pd),
        "q_proj": norm(ks[1], (L, D, H * hd)),
        "k_proj": norm(ks[2], (L, D, KV * hd)),
        "v_proj": norm(ks[3], (L, D, KV * hd)),
        "o_proj": norm(ks[4], (L, H * hd, D)),
        "post_attention_layernorm": jnp.ones((L, D), pd),
        "gate": norm(ks[5], (L, D, E)),
        "expert_gate_proj": norm(ks[6], (L, E, D, Fm)),
        "expert_up_proj": norm(ks[7], (L, E, D, Fm)),
        "expert_down_proj": norm(ks[8], (L, E, Fm, D)),
    }
    if cfg.num_shared_experts:
        Fs = cfg.moe_intermediate_size * cfg.num_shared_experts
        layers.update({
            "shared_gate_proj": norm(ks[9], (L, D, Fs)),
            "shared_up_proj": norm(ks[10], (L, D, Fs)),
            "shared_down_proj": norm(ks[11], (L, Fs, D)),
        })
    return {
        "embed_tokens": norm(ks[0], (V, D)),
        "layers": layers,
        "norm": jnp.ones((D,), pd),
        "lm_head": norm(jax.random.fold_in(key, 99), (D, V)),
    }


def param_specs(cfg: MoeConfig, pp: bool = False) -> Dict[str, Any]:
    """Sharding table: experts over 'ep' (expert parallelism — the
    reference's moe_group), expert matrices 2D-sharded over
    (sharding, mp) like dense weights; attention same as llama."""
    lspec = "pp" if pp else None
    layers = {
        "input_layernorm": P(lspec, None),
        "q_proj": P(lspec, "sharding", "mp"),
        "k_proj": P(lspec, "sharding", "mp"),
        "v_proj": P(lspec, "sharding", "mp"),
        "o_proj": P(lspec, "mp", "sharding"),
        "post_attention_layernorm": P(lspec, None),
        "gate": P(lspec, None, None),
        "expert_gate_proj": P(lspec, "ep", "sharding", "mp"),
        "expert_up_proj": P(lspec, "ep", "sharding", "mp"),
        "expert_down_proj": P(lspec, "ep", "mp", "sharding"),
    }
    if cfg.num_shared_experts:
        layers.update({
            "shared_gate_proj": P(lspec, "sharding", "mp"),
            "shared_up_proj": P(lspec, "sharding", "mp"),
            "shared_down_proj": P(lspec, "mp", "sharding"),
        })
    return {
        "embed_tokens": P("mp", "sharding"),
        "layers": layers,
        "norm": P(None),
        "lm_head": P("sharding", "mp"),
    }


def moe_block(x: jax.Array, lp: Dict[str, jax.Array], cfg: MoeConfig,
              mesh=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """x: [B, S, D] → (y, aux). Routed experts + optional shared expert.

    GShard GROUPED dispatch: capacity is per group (group = batch row), so
    routing state is [B, S, k] indices + an inverse map [B, E, C(S)] —
    linear in total tokens. Dispatch gathers token rows into [B, E, C, D]
    (combine gathers back), so nothing materializes the round-1 [B,S,E,C]
    one-hot tensors whose memory scaled with E*C (VERDICT item 4). Groups
    align with the dp/sharding batch axes, so each data shard routes
    independently and the gathers stay shard-local under GSPMD — the same
    locality the reference gets from per-rank all_to_all over the
    moe_group; the expert einsums sharded P('ep') still make GSPMD insert
    the EP all_to_all. On a single TPU chip (mesh=None) the two gathers run
    the Pallas ragged dispatch kernel (kernels.moe_dispatch, SURVEY.md §7
    M7) — under a mesh they stay jnp gathers, which GSPMD can partition."""
    B, S, D = x.shape
    cd = cfg.dtype
    k = cfg.num_experts_per_tok
    E = cfg.num_experts
    C = cfg.capacity(S)

    logits = x.astype(jnp.float32) @ lp["gate"].astype(jnp.float32)  # [B,S,E]
    # routing's token-inverse map is NOT consumed here: dispatch/combine
    # need the POSITION-inverse map (inv_pos below) too, and deriving the
    # token map from it (inv_pos // k) keeps the pair consistent by
    # construction instead of by parallel scatters
    eidx, slot, probs, valid, _, aux = jax.vmap(
        lambda lg: top_k_routing(lg, k, C))(logits)
    aux = jax.tree.map(jnp.mean, aux)

    from jax.ad_checkpoint import checkpoint_name
    from ..kernels.moe_dispatch import (combine_gather, combine_wsum,
                                        dispatch_gather)
    # both directions of dispatch AND their gradients are masked row
    # gathers over a pair of inverse index maps (slot assignment is
    # injective — kernels.moe_dispatch): flat maps (token, choice) → slot,
    # inv_pos maps slot → token position. Nothing in the MoE path scatters.
    flat = jnp.where(valid, eidx * C + slot, -1).reshape(B, S * k)
    if mesh is None:
        # single chip: EXPERT-LEADING global layout [E, B*C, D]. The
        # (b, e)-batched einsums made XLA shuffle every expert tensor
        # between {b-major} and {e-major} layouts in fwd AND bwd (~170
        # ms/step of pure transposes on the config-4 bench); with e
        # leading and one flat row index space, dispatch/GEMMs/combine
        # all agree on the layout. Rows: slot (e, b, c) at e*B*C + b*C + c,
        # token position (b, s, j) at b*S*k + s*k + j.
        boff = (jnp.arange(B, dtype=jnp.int32) * C)[:, None]
        flat_g = jnp.where(flat >= 0, (eidx * (B * C)).reshape(B, S * k)
                           + boff + slot.reshape(B, S * k), -1)
        flat_g = flat_g.reshape(1, B * S * k)
        safe = jnp.where(flat_g >= 0, flat_g, E * B * C)
        inv_pos = jnp.full((E * B * C + 1,), -1, jnp.int32).at[safe[0]].set(
            jnp.arange(B * S * k, dtype=jnp.int32), mode="drop")[None, :-1]
        inv_tok = jnp.where(inv_pos >= 0, inv_pos // k, -1)
        flat_g, inv_pos, inv_tok, probs = (
            checkpoint_name(t, "moe_routing")
            for t in (flat_g, inv_pos, inv_tok, probs))
        # NOT the fused gather_mlp kernel (r5 negative result, measured
        # standalone at flagship shapes: fused dispatch+gate/up 18.6 ms
        # vs 16.4 ms for gather_rows + XLA einsums — the per-block row
        # DMA does not hide under the per-step MXU work at bm=128, the
        # largest block the weight-resident formulation can afford in
        # scoped VMEM; kernels.moe_dispatch.gather_mlp keeps the kernel
        # + tests as the documented experiment, VERDICT r4 next-4)
        expert_in = dispatch_gather(
            x.reshape(1, B * S, D).astype(cd), inv_tok, flat_g, k,
            True).reshape(E, B * C, D)
        g = jnp.einsum("emd,edf->emf", expert_in,
                       lp["expert_gate_proj"].astype(cd))
        u = jnp.einsum("emd,edf->emf", expert_in,
                       lp["expert_up_proj"].astype(cd))
        expert_out = jnp.einsum("emf,efd->emd", jax.nn.silu(g) * u,
                                lp["expert_down_proj"].astype(cd))
        # FUSED weighted combine: y[t] = sum_j probs[t,j]·eout[slot(t,j)]
        # in one kernel — the unfused gather-to-[B,S,k,D] + einsum path
        # cost ~100 ms/step of T(2,128)-tiled reshape/reduce traffic
        # (round-4 profile); its backward gathers dy rows once for BOTH
        # d_eout and d_probs (kernels.moe_dispatch.combine_wsum)
        idx_tk = jnp.clip(flat_g, 0).reshape(1, B * S, k)
        w_tk = jnp.where(flat_g >= 0,
                         probs.reshape(1, B * S * k).astype(jnp.float32),
                         0.0).reshape(1, B * S, k)
        y = combine_wsum(expert_out.reshape(1, E * B * C, D), idx_tk,
                         w_tk, inv_pos, True).reshape(B, S, D).astype(cd)
    else:
        # under GSPMD: per-batch-row index space — groups align with the
        # dp/sharding batch shards so the gathers stay shard-local
        safe = jnp.where(flat >= 0, flat, E * C)
        pos_ids = jnp.broadcast_to(
            jnp.arange(S * k, dtype=jnp.int32)[None], (B, S * k))
        inv_pos = jax.vmap(
            lambda ip, s, p: ip.at[s].set(p, mode="drop"))(
                jnp.full((B, E * C + 1), -1, jnp.int32), safe,
                pos_ids)[:, :-1]
        inv_tok = jnp.where(inv_pos >= 0, inv_pos // k, -1)
        flat, inv_pos, inv_tok, probs = (
            checkpoint_name(t, "moe_routing")
            for t in (flat, inv_pos, inv_tok, probs))
        # r5 (VERDICT r4 next-3): on TPU the batch-local gathers run the
        # SAME fused Pallas kernels as the single-chip bench, shard_mapped
        # over the batch shards (a bare pallas_call is opaque to GSPMD —
        # wrapping it manual over the batch axes is exactly the shard-
        # local computation the jnp path relied on GSPMD to discover).
        # jnp stays the fallback off-TPU and inside pipeline stages
        # (manual-over-pp shard_map cannot nest another shard_map).
        from ..kernels.flash_attention import _use_pallas
        fused = _use_pallas(x) and not _llama.in_manual_axis("pp")
        if fused:
            from jax import shard_map
            bax = ("dp", "sharding")
            expert_in = shard_map(
                lambda xs, it, fl: dispatch_gather(xs, it, fl, k, True),
                mesh=mesh,
                in_specs=(P(bax, None, None), P(bax, None), P(bax, None)),
                out_specs=P(bax, None, None), check_vma=False,
            )(x.astype(cd), inv_tok, flat)
            expert_in = expert_in.reshape(B, E, C, D)
        else:
            expert_in = dispatch_gather(x.astype(cd), inv_tok, flat, k,
                                        False).reshape(B, E, C, D)
        g = jnp.einsum("becd,edf->becf", expert_in,
                       lp["expert_gate_proj"].astype(cd))
        u = jnp.einsum("becd,edf->becf", expert_in,
                       lp["expert_up_proj"].astype(cd))
        expert_out = jnp.einsum("becf,efd->becd", jax.nn.silu(g) * u,
                                lp["expert_down_proj"].astype(cd))
        if fused:
            # FUSED weighted combine per batch shard (same contract as
            # the single-chip branch: idx pre-clipped, w pre-zeroed)
            idx_tk = jnp.clip(flat, 0).reshape(B, S, k)
            w_tk = jnp.where(flat >= 0, probs.reshape(B, S * k)
                             .astype(jnp.float32), 0.0).reshape(B, S, k)
            y = shard_map(
                lambda eo, it, wt, ip: combine_wsum(eo, it, wt, ip, True),
                mesh=mesh,
                in_specs=(P(bax, None, None), P(bax, None, None),
                          P(bax, None, None), P(bax, None)),
                out_specs=P(bax, None, None), check_vma=False,
            )(expert_out.reshape(B, E * C, D), idx_tk, w_tk,
              inv_pos).astype(cd)
        else:
            got = combine_gather(expert_out.reshape(B, E * C, D), flat,
                                 inv_pos, False).reshape(B, S, k, D)
            # combine: y[b,s] = Σ_j probs[b,s,j] · expert_out[slot(b,s,j)]
            y = jnp.einsum("bskd,bsk->bsd", got, probs.astype(cd))

    if cfg.num_shared_experts:
        sg = x @ lp["shared_gate_proj"].astype(cd)
        su = x @ lp["shared_up_proj"].astype(cd)
        y = y + (jax.nn.silu(sg) * su) @ lp["shared_down_proj"].astype(cd)
    return y, aux


def _decoder_body(carry, lp, cfg: MoeConfig, lcfg, cos, sin, mesh,
                  constrain=None):
    """One MoE decoder layer on the (x, lb, zl) carry — the SINGLE source
    for both the plain scan (forward) and the pipeline stage (forward_pp);
    `constrain` optionally re-annotates activation sharding."""
    h, lb, zl = carry
    norm = _llama._make_norm(cfg, mesh)  # fused kernel, shard_mapped
    # under a mesh (r5; jnp inside pipeline stages — llama.in_manual_axis)
    a = norm(h, lp["input_layernorm"])
    h = h + _llama._attention(a, lp, lcfg, cos, sin, mesh)
    a = norm(h, lp["post_attention_layernorm"])
    y, aux = moe_block(a, lp, cfg, mesh)
    h = h + y
    if constrain is not None:
        h = constrain(h)
    return (h, lb + aux["load_balance_loss"], zl + aux["router_z_loss"])


def _backbone(params, tokens, cfg: MoeConfig, mesh=None):
    """Embed + MoE decoder stack → (pre-norm x [B,S,D], aux losses)."""
    lcfg = _llama_cfg(cfg)
    cd = cfg.dtype
    x = jnp.take(params["embed_tokens"], tokens, axis=0).astype(cd)
    cos, sin = rope_freqs(cfg.head_dim, tokens.shape[1], cfg.rope_theta,
                          jnp.float32)

    def maybe_constrain(h):
        if mesh is not None:
            from jax.sharding import NamedSharding
            h = jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh, _llama.act_spec()))
        return h

    x = maybe_constrain(x)

    def body(carry, lp):
        return _decoder_body(carry, lp, cfg, lcfg, cos, sin, mesh,
                             constrain=maybe_constrain), None

    if cfg.remat:
        # save the (tiny) routing index maps so the backward refwd skips
        # the router; everything big is still rematerialized
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "moe_routing"))
    (x, lb, zl), _ = jax.lax.scan(
        body, (x, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        params["layers"])
    L = cfg.num_hidden_layers
    return x, {"load_balance_loss": lb / L, "router_z_loss": zl / L}


def forward(params: Dict[str, Any], tokens: jax.Array, cfg: MoeConfig,
            mesh=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """tokens [B,S] → (logits [B,S,V] f32, aux losses)."""
    cd = cfg.dtype
    x, aux = _backbone(params, tokens, cfg, mesh)
    x = rms_norm_ref(x, params["norm"], cfg.rms_norm_eps)
    logits = (x.astype(cd) @ params["lm_head"].astype(cd)).astype(jnp.float32)
    return logits, aux


def forward_pp(params: Dict[str, Any], tokens: jax.Array, cfg: MoeConfig,
               mesh, num_microbatches: int) -> Tuple[jax.Array,
                                                     Dict[str, jax.Array]]:
    """Pipeline-parallel MoE forward: decoder stages run the compiled GPipe
    schedule over the mesh's `pp` axis, composing with ep/sharding/mp
    (reference: DeepSeek-class recipes run pp x ep). The router aux losses
    ride the pipe as extra pytree-buffer channels — each stage adds its
    layers' load-balance and z losses to the per-microbatch accumulators
    (parallel.pipeline.gpipe_apply carries arbitrary pytrees)."""
    from ..parallel.pipeline import pipelined, stack_stages

    n = mesh.shape["pp"]
    B, S = tokens.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    L = cfg.num_hidden_layers
    lcfg = _llama_cfg(cfg)
    cd = cfg.dtype
    cos, sin = rope_freqs(cfg.head_dim, S, cfg.rope_theta, jnp.float32)
    stage_params = stack_stages(params["layers"], n)

    def stage_fn(local_layers, buf):
        def body(carry, lp):
            return _decoder_body(carry, lp, cfg, lcfg, cos, sin, mesh), None
        (x, lb, zl), _ = jax.lax.scan(
            body, (buf["x"], buf["lb"], buf["zl"]), local_layers)
        return {"x": x, "lb": lb, "zl": zl}

    x = jnp.take(params["embed_tokens"], tokens, axis=0).astype(cd)
    mb = {
        "x": x.reshape((M, B // M) + x.shape[1:]),
        "lb": jnp.zeros((M,), jnp.float32),
        "zl": jnp.zeros((M,), jnp.float32),
    }
    outs = pipelined(stage_fn, mesh, remat=cfg.remat)(stage_params, mb)
    x = outs["x"].reshape(B, S, -1)
    x = rms_norm_ref(x, params["norm"], cfg.rms_norm_eps)
    logits = (x.astype(cd) @ params["lm_head"].astype(cd)).astype(jnp.float32)
    aux = {"load_balance_loss": jnp.mean(outs["lb"]) / L,
           "router_z_loss": jnp.mean(outs["zl"]) / L}
    return logits, aux


def loss_and_grad_pp(params: Dict[str, Any], tokens: jax.Array,
                     cfg: MoeConfig, mesh, num_microbatches: int,
                     virtual_pp: int = 1):
    """Fused loss + grads for MoE through the compiled 1F1B schedule.

    Reference analog: DeepSeek-class MoE under fleet's 1F1B scheduler
    (SURVEY.md §2.3 EP row; VERDICT r2 missing 5 — MoE+pp previously fell
    back to GPipe because 1F1B's activation contract was a single array).
    The router aux-loss accumulators ride the pipe as extra PYTREE buffer
    channels — pipeline.one_f_one_b carries arbitrary pytrees now — and
    their cotangents flow back up the same ring, so load-balance/z-loss
    gradients reach every stage's routers. virtual_pp > 1 uses the
    interleaved 1F1B (O(v·pp) residency) with the same pytree buffers.
    Returns (loss, grads) with grads matching the params tree."""
    from ..parallel.pipeline import run_1f1b

    n = mesh.shape["pp"]
    B, S = tokens.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    L = cfg.num_hidden_layers
    lcfg = _llama_cfg(cfg)
    cd = cfg.dtype
    cos, sin = rope_freqs(cfg.head_dim, S, cfg.rope_theta, jnp.float32)
    f32 = jnp.float32

    def stage_fn(local_layers, buf):
        def body(carry, lp):
            return _decoder_body(carry, lp, cfg, lcfg, cos, sin, mesh), None
        (x, lb, zl), _ = jax.lax.scan(
            body, (buf["x"], buf["lb"], buf["zl"]), local_layers)
        return {"x": x, "lb": lb, "zl": zl}

    def first_fn(embed, tok_mb):
        return {"x": jnp.take(embed, tok_mb, axis=0).astype(cd),
                "lb": jnp.zeros((), f32), "zl": jnp.zeros((), f32)}

    def last_fn(lp, buf, tok_mb):
        x = rms_norm_ref(buf["x"], lp["norm"], cfg.rms_norm_eps)
        logits = (x.astype(cd) @ lp["lm_head"].astype(cd)).astype(f32)
        ce = _llama._mb_loss(logits, tok_mb)
        return (ce + cfg.router_aux_loss_coef * buf["lb"] / L
                + cfg.router_z_loss_coef * buf["zl"] / L)

    first_params = params["embed_tokens"]
    last_params = {"norm": params["norm"], "lm_head": params["lm_head"]}
    toks_mb = tokens.reshape((M, B // M) + tokens.shape[1:])
    loss, g_layers, g_f, g_l = run_1f1b(
        stage_fn, first_fn, last_fn, mesh, params["layers"], first_params,
        last_params, toks_mb, n_stages=n, virtual_pp=virtual_pp)
    grads = {"embed_tokens": g_f, "layers": g_layers,
             "norm": g_l["norm"], "lm_head": g_l["lm_head"]}
    grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
    return loss, grads


def loss_fn(params, tokens, cfg: MoeConfig, mesh=None,
            pp_microbatches=None, pp_virtual: int = 1):
    """Next-token CE + router aux losses (full-shape roll+mask, same
    rationale as llama.loss_fn). pp_microbatches: with a mesh whose pp
    axis > 1, run the decoder through the compiled GPipe schedule.
    pp_virtual > 1 under the GPipe forward is not implemented for MoE —
    use schedule='1f1b' (loss_and_grad_pp handles virtual_pp with the
    pytree aux channels)."""
    if pp_virtual > 1:
        raise NotImplementedError(
            "interleaved virtual-pp under the MoE GPipe forward is not "
            "implemented (paddle_tpu/nlp/moe.py) — use pp_schedule='1f1b', "
            "whose interleaved_one_f_one_b carries the aux-loss pytree")
    if (pp_microbatches and mesh is not None
            and "pp" in mesh.axis_names and mesh.shape["pp"] > 1):
        logits, aux = forward_pp(params, tokens, cfg, mesh, pp_microbatches)
        ce = _llama._mb_loss(logits, tokens)
    else:
        x, aux = _backbone(params, tokens, cfg, mesh)
        x = rms_norm_ref(x, params["norm"], cfg.rms_norm_eps)
        # fused head+CE: no [B, S, V] f32 logits materialization
        ce = _llama.fused_head_ce(
            x.astype(cfg.dtype),
            params["lm_head"].astype(cfg.dtype), tokens)
    return (ce + cfg.router_aux_loss_coef * aux["load_balance_loss"]
            + cfg.router_z_loss_coef * aux["router_z_loss"])


def num_params(cfg: MoeConfig) -> int:
    D, V, L = cfg.hidden_size, cfg.vocab_size, cfg.num_hidden_layers
    E, Fm = cfg.num_experts, cfg.moe_intermediate_size
    H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    per = (2 * D + D * (H + 2 * KV) * hd + H * hd * D
           + D * E + 3 * E * D * Fm)
    if cfg.num_shared_experts:
        per += 3 * D * Fm * cfg.num_shared_experts
    return V * D + L * per + D + D * V


def active_params(cfg: MoeConfig) -> int:
    """Parameters touched per token (the 'A14B' in Qwen2-57B-A14B)."""
    D, V, L = cfg.hidden_size, cfg.vocab_size, cfg.num_hidden_layers
    Fm = cfg.moe_intermediate_size
    H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    per = (2 * D + D * (H + 2 * KV) * hd + H * hd * D + D * cfg.num_experts
           + 3 * D * Fm * cfg.num_experts_per_tok)
    if cfg.num_shared_experts:
        per += 3 * D * Fm * cfg.num_shared_experts
    return V * D + L * per + D + D * V


def flops_per_token(cfg: MoeConfig, seq_len: int) -> float:
    """Approx. train FLOPs/token over ACTIVE params (the MoE convention —
    only routed + shared experts do work), same 6x fwd+bwd and
    causal-halved attention accounting as llama.flops_per_token."""
    D, Fm, L = (cfg.hidden_size, cfg.moe_intermediate_size,
                cfg.num_hidden_layers)
    H, KV, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    matmul = L * (D * (H + 2 * KV) * hd + H * hd * D + D * cfg.num_experts
                  + 3 * D * Fm * (cfg.num_experts_per_tok
                                  + cfg.num_shared_experts)) \
        + cfg.vocab_size * D
    attn = L * H * hd * seq_len
    return 6.0 * (matmul + attn)
