"""paddle_tpu.nlp.ragged_attention — Pallas ragged paged-attention.

The serving decode path is gather/HBM-bound: `_paged_gqa_attention`
(nlp/paged.py) gathers the FULL block-table width per step in XLA —
every request pays `M * block_size` keys of HBM traffic no matter how
short its live sequence is, and BENCH shows decode ~25x below prefill
throughput because of it. This module is the kernel half of the fix
(design: "Ragged Paged Attention: A High-Performance and Flexible LLM
Inference Kernel for TPU", PAPERS.md, arxiv 2604.15464):

  * grid over (request row, query tile, KV-block-chunk) with the block
    table and per-(row, tile) LIVE chain lengths fed as scalar
    prefetch — the BlockSpec index map resolves each grid step's pool
    block id from the table before the kernel body runs, so the KV
    gather IS the pipeline's DMA (no XLA gather materializing
    [B, M*bs, KV, hd] in HBM); the query tile (`q_tile`, default 128)
    bounds VMEM residency so wide prefill buckets fit a core;
  * dead chunks (past a request's live chain, or all of a padded /
    inactive row) clamp their index map to the previous live block —
    Pallas skips the re-fetch of an unchanged block, so a request's HBM
    traffic tracks ceil(len/block_size) blocks, not the table width;
  * a flash-style online softmax (running max / sum / accumulator in
    VMEM scratch, carried across the block-chunk grid dimension)
    finalizes each row at its LAST live chunk;
  * per-query causal masking (`key position j <= positions[row, p]`)
    matches the XLA path exactly, so the one kernel serves single-token
    decode rows, bucketed/chunked cached-prefix prefill rows, AND the
    mixed decode+prefill batch of the fused step — the Ragged Paged
    Attention mixed-mode shape. Invalid (padded) query rows produce
    zeros instead of the XLA path's never-read garbage.

Tensor parallel (ROADMAP direction 7): a `mesh=` kwarg runs the same
kernel under `shard_map` — each device executes the per-device
pallas_call on its contiguous head shard (GSPMD cannot partition a
pallas_call, but it can stitch per-shard kernel outputs on the head
axis), with the block table, live lengths and dequant scales
replicated. Per-head math is shard-independent, so the sharded result
is bit-identical to the mesh-off kernel — the GSPMD-paper property
that sharded programs inherit single-device kernels.

The XLA gather path stays the reference implementation: CPU runs it by
default (`resolve_attention_impl("auto")`), and the parity suite
(tests/test_ragged_attention.py) pins pallas==xla on decode, prefill,
fused and prefix-cache-COW batches — on CPU via `interpret=True`, which
this wrapper selects automatically off-TPU.

int8 paged KV (ROADMAP direction 4, the PR 6 follow-on): when the pool
stores int8 codes, per-(layer, block) abs-max scales ride scalar
prefetch next to the block table and the kernel dequantizes each
gathered block INSIDE the block-chunk loop (quantization.kv's
`dequantize`, the same math as the XLA path's after-the-gather
reference) — the gather-fused structure makes the dequant free, so a
quantized request's HBM traffic is its int8 block bytes, ~half the fp
bytes the unquantized chain moves.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..quantization import kv as kvq

__all__ = ["ragged_paged_attention", "resolve_attention_impl"]

_NEG_INF = -1e30


def resolve_attention_impl(impl: str) -> str:
    """Resolve an `attention_impl` choice to a concrete backend.

    "auto" picks "pallas" on TPU and "xla" everywhere else (the XLA
    gather path is the reference/fallback implementation and the only
    compiled path on CPU — pallas off-TPU runs in interpret mode, which
    is for parity testing, not speed). "pallas" and "xla" pass through;
    anything else raises ValueError.
    """
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(
            f"attention_impl must be 'auto', 'pallas' or 'xla', "
            f"got {impl!r}")
    return impl


def _rpa_kernel(*refs, bs: int, scale: float, quantized: bool,
                suffix: bool = False, nchunks: int = 0):
    """One (row, query-tile, block-chunk) grid step of the ragged kernel.

    Refs (per BlockSpec):
      pos_ref/val_ref [1, Pt] int32 — this tile's query positions /
      validity; q_ref [1, Pt, H, hd]; k_ref/v_ref [1, bs, KV, hd] — THE
      pool block this chunk's index map resolved from the prefetched
      table; o_ref [1, Pt, H, hd]; scratch acc [Pt, H, hd] f32,
      m/l [Pt, H] f32. `live_ref` is per (row, tile): a tile's chain
      walk stops at ITS OWN last visible block, not the row's.
      `quantized` adds ks_ref/vs_ref [N] f32 per-block dequant scales
      to the scalar prefetch: the block's codes dequantize right after
      the pipeline DMA lands them in VMEM — the fused-dequant gather.

    `suffix` adds the speculative verify's in-register suffix slab:
    sk_ref/sv_ref [1, S, KV, hd] (this row's not-yet-committed K/V —
    the packed draft chain or tree) and svis_ref [1, Pt, S] int32 (per-
    query slab visibility: the chain's causal triangle or the tree's
    ancestor mask). The grid grows ONE extra chunk (c == nchunks, past
    the table width): the pool sweep stays the int8-gathered block loop
    unchanged, and the final chunk folds the slab's scores into the
    same online softmax and finalizes there — every row finalizes at
    the slab chunk, since slab visibility is independent of the pool
    chain length.
    """
    import jax.experimental.pallas as pl

    if quantized:
        (tab_ref, live_ref, ks_ref, vs_ref, pos_ref, val_ref, q_ref,
         k_ref, v_ref, *rest) = refs
    else:
        (tab_ref, live_ref, pos_ref, val_ref, q_ref, k_ref, v_ref,
         *rest) = refs
        ks_ref = vs_ref = None
    if suffix:
        sk_ref, sv_ref, svis_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
        sk_ref = sv_ref = svis_ref = None
    r, t, c = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nlive = live_ref[r, t]

    @pl.when(c == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(c < nlive)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale          # [P, H, hd]
        if quantized:
            # dequantize THIS chunk's block under its prefetched scale
            # (chain chunk c of row r is pool block tab[r, c] — live,
            # since c < nlive here): the same quantization.kv math the
            # XLA path applies after its gather
            b = jnp.maximum(tab_ref[r, c], 0)
            k = kvq.dequantize(k_ref[0], ks_ref[b])       # [bs, KV, hd]
            v = kvq.dequantize(v_ref[0], vs_ref[b])
        else:
            k = k_ref[0].astype(jnp.float32)              # [bs, KV, hd]
            v = v_ref[0].astype(jnp.float32)
        P, H, hd = q.shape
        KV = k.shape[1]
        rep = H // KV
        # grouped-GQA scores against this ONE pool block: query head
        # h = kv*rep + r_h reads kv head kv — the same head grouping as
        # q.reshape(B, P, KV, rep, hd) in the XLA path
        qg = q.reshape(P, KV, rep, hd)
        s = jnp.einsum("pkrd,tkd->pkrt", qg, k,
                       preferred_element_type=jnp.float32)
        s = s.reshape(P, H, bs)
        # per-query causal visibility at ABSOLUTE key position
        # j = c*bs + t (chain position, not pool position), masked by
        # query validity so padded rows accumulate nothing
        kpos = c * bs + jax.lax.broadcasted_iota(jnp.int32, (P, bs), 1)
        vis = (kpos <= pos_ref[0][:, None]) & \
              (val_ref[0] != 0)[:, None]                  # [P, bs]
        s = jnp.where(vis[:, None, :], s, _NEG_INF)
        m_prev = m_ref[...]                               # [P, H]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        # exp(s - m) alone is 1.0 for fully-masked rows (s == m ==
        # _NEG_INF) — the explicit vis multiply keeps them at zero
        p = jnp.exp(s - m_new[:, :, None])
        p = jnp.where(vis[:, None, :], p, 0.0)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("pkrt,tkd->pkrd", p.reshape(P, KV, rep, bs), v,
                        preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha[:, :, None] \
            + pv.reshape(P, H, hd)
        m_ref[...] = m_new

    if suffix:
        # the slab chunk (c == nchunks, past every pool block): fold
        # the suffix slab's scores into the SAME online softmax. Slab
        # rows are full precision (verify-then-commit: these K/V have
        # not been quantized or committed yet), visibility is the
        # prefetched per-query slab mask AND query validity.
        @pl.when(c == nchunks)
        def _suffix_fold():
            q = q_ref[0].astype(jnp.float32) * scale      # [P, H, hd]
            k = sk_ref[0].astype(jnp.float32)             # [S, KV, hd]
            v = sv_ref[0].astype(jnp.float32)
            P, H, hd = q.shape
            S, KV, _ = k.shape
            rep = H // KV
            qg = q.reshape(P, KV, rep, hd)
            s = jnp.einsum("pkrd,skd->pkrs", qg, k,
                           preferred_element_type=jnp.float32)
            s = s.reshape(P, H, S)
            vis = (svis_ref[0] != 0) & \
                  (val_ref[0] != 0)[:, None]              # [P, S]
            s = jnp.where(vis[:, None, :], s, _NEG_INF)
            m_prev = m_ref[...]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, :, None])
            p = jnp.where(vis[:, None, :], p, 0.0)
            l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1)
            pv = jnp.einsum("pkrs,skd->pkrd",
                            p.reshape(P, KV, rep, S), v,
                            preferred_element_type=jnp.float32)
            acc_ref[...] = acc_ref[...] * alpha[:, :, None] \
                + pv.reshape(P, H, hd)
            m_ref[...] = m_new
            l = l_ref[...]
            o = acc_ref[...] / jnp.where(l > 0.0, l, 1.0)[:, :, None]
            o_ref[0] = o.astype(o_ref.dtype)
        return

    # finalize at the row's last LIVE chunk (c == 0 for an all-padded
    # row: init just zeroed the accumulators, so the row emits zeros)
    @pl.when(c == jnp.maximum(nlive - 1, 0))
    def _finalize():
        l = l_ref[...]
        o = acc_ref[...] / jnp.where(l > 0.0, l, 1.0)[:, :, None]
        o_ref[0] = o.astype(o_ref.dtype)


def _shard_specs(mesh_axis: str, quantized: bool, suffix: bool):
    """PartitionSpecs for `shard_map`-wrapping the kernel on a 1-D mesh.

    Positional layout mirrors the pallas_call argument order: scalar
    prefetch first (table, live[, k_scale, v_scale] — all REPLICATED:
    every shard walks the same block chains under the same per-block
    dequant scales), then positions/val (replicated), then the
    head-carrying operands q, k_pool, v_pool[, suffix_k, suffix_v]
    split on their head axis (dim 2 for all five), then suffix_vis
    (replicated — visibility is a per-query/per-slab-row fact, not a
    per-head one). The output activation [R, P, H, hd] splits on the
    same head axis.
    """
    from jax.sharding import PartitionSpec as P

    repl = P()
    head = P(None, None, mesh_axis, None)
    specs = (repl, repl)
    if quantized:
        specs += (repl, repl)
    specs += (repl, repl, head, head, head)
    if suffix:
        specs += (head, head, repl)
    return specs, head


def ragged_paged_attention(q, k_pool, v_pool, table, positions, valid=None,
                           *, k_scale=None, v_scale=None,
                           suffix_k=None, suffix_v=None, suffix_vis=None,
                           q_tile: int = 128, interpret=None,
                           mesh=None, mesh_axis: str = "mp"):
    """Paged GQA attention walking only each request's live block chain.

    Drop-in twin of the XLA `_paged_gqa_attention` gather path
    (nlp/paged.py) with the same per-query-causal semantics:

      q [R, P, H, hd]; k_pool/v_pool [N, bs, KV, hd]; table [R, M] int32
      pool block ids per row; positions [R, P] int32 absolute query
      positions (query p sees chain keys j <= positions[r, p]);
      valid [R, P] bool query mask (None = all valid). Returns
      [R, P, H, hd] in q's dtype; INVALID queries return zeros (the XLA
      path leaves never-read garbage there).

    k_scale/v_scale [N] f32 mark an int8 pool (kv_dtype="int8"): the
    per-block abs-max scales ride scalar prefetch next to the table and
    each live chunk's codes dequantize INSIDE the block loop, right
    after the pipeline DMA — the gather moves int8 bytes, the dequant
    is fused compute. Dead chunks still skip their fetch, so a
    quantized request's HBM traffic is ~half its fp block bytes.

    The query dimension tiles at the largest divisor of P that is
    <= `q_tile` rows per grid step (q_tile itself for the serving
    path's power-of-two buckets; worst case 1 for a prime P, which
    trades grid overhead for the VMEM bound), bounding VMEM residency
    — scratch + q/o blocks scale with the TILE, not the full prefill
    bucket width, so a 512-wide bucket at production head counts still
    fits a core's VMEM. Per (row, tile) live chain lengths —
    ceil((max valid position in the tile + 1) / bs) — ride scalar
    prefetch next to the table, so the kernel's grid work and HBM
    traffic track the tokens actually cached, not the table width: a
    tile with no valid query (padded slot, inactive decode row of the
    fused batch, all-pad bucket tail) touches no blocks at all, and an
    early tile of a long suffix stops at its own last visible block.

    suffix_k/suffix_v [R, S, KV, hd] add the speculative verify's
    in-register suffix slab (the packed draft chain or tree — K/V that
    exist ONLY in registers until the accepted path commits) as a
    kernel operand: the grid grows one chunk past the table width and
    the final chunk folds the slab's scores into the same online
    softmax, so the pool sweep stays the int8-gathered block loop
    instead of falling back to the XLA concat path. suffix_vis
    [R, P, S] (bool/int) gives each query its visible slab rows — the
    chain's causal triangle or the tree's ancestor mask; invalid
    queries still emit zeros. The XLA formulation in
    `paged._spec_gqa_attention` stays the bit-stable parity reference.

    `mesh` (a 1-D jax.sharding.Mesh over axis `mesh_axis`) runs the
    kernel tensor-parallel: GSPMD cannot partition a pallas_call, so
    the call is wrapped in `shard_map` with q/k_pool/v_pool (and the
    suffix slab) split on their head axis and everything else — block
    table, live lengths, positions, validity, dequant scales, slab
    visibility — replicated. Each device runs THIS kernel on its
    contiguous head shard: per-shard H/tp query heads keep the same
    GQA group size rep = H/KV, and local head h maps to local kv head
    h // rep exactly as the global mapping does (the serving mesh's
    contiguous-shard convention, serving/tp.py), so every head's math
    is untouched and the head-axis concatenation makes the sharded
    result BIT-identical to the mesh-off kernel. Requires H and KV
    divisible by the mesh axis size.

    `interpret=None` auto-selects Pallas interpret mode off-TPU — the
    CPU CI parity path. Tolerance vs XLA is tight-but-not-bitwise: the
    online softmax reassociates the reduction.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    R, P, H, hd = q.shape
    N, bs, KV, _ = k_pool.shape
    M = table.shape[1]
    if valid is None:
        valid = jnp.ones((R, P), bool)
    val = valid.astype(jnp.int32)
    positions = positions.astype(jnp.int32)
    table = table.astype(jnp.int32)
    # largest divisor of P that fits the tile budget: bucketed widths
    # are powers of two, so this is q_tile itself for every P > 128 the
    # serving path produces; an awkward P (non-pow2 bucket caps, exact
    # unbucketed shapes) still tiles at its largest fitting divisor
    # rather than silently reverting to a VMEM-unbounded whole-row tile
    q_tile = max(1, min(q_tile, P))
    Pt = max(d for d in range(1, q_tile + 1) if P % d == 0)
    T = P // Pt
    # live chain blocks per (row, tile): valid query p needs chain keys
    # up to position positions[r, p], all written before this call — so
    # a tile's walk stops at ceil((its max valid position + 1) / bs)
    live_tok = jnp.max(
        jnp.where(valid, positions + 1, 0).reshape(R, T, Pt), axis=2)
    live = ((live_tok + bs - 1) // bs).astype(jnp.int32)

    quantized = k_scale is not None
    suffix = suffix_k is not None

    def _tile_map(r, t, c, tab, live, *scales):
        return (r, t)

    def _tile3_map(r, t, c, tab, live, *scales):
        return (r, t, 0, 0)

    def _kv_map(r, t, c, tab, live, *scales):
        # chunk c of (row r, tile t) reads pool block table[r, c]; DEAD
        # chunks (c >= live[r, t]) re-resolve to the last live block —
        # an unchanged index, so the pipeline skips the fetch (the
        # suffix grid's extra slab chunk clamps here too)
        j = jnp.minimum(c, jnp.maximum(live[r, t] - 1, 0))
        return (jnp.maximum(tab[r, j], 0), 0, 0, 0)

    def _suffix_map(r, t, c, tab, live, *scales):
        # the row's whole slab, fetched once per (row, tile)
        return (r, 0, 0, 0)

    def _svis_map(r, t, c, tab, live, *scales):
        return (r, t, 0)

    nscal = 4 if quantized else 2
    args = [table, live]
    if quantized:
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    args += [positions, val, q, k_pool, v_pool]
    if suffix:
        S = suffix_k.shape[1]
        args += [suffix_k, suffix_v, suffix_vis.astype(jnp.int32)]

    def _kernel_call(*ops):
        # per-device body: head counts come from the LOCAL operand
        # shapes — under shard_map each device sees its contiguous head
        # shard (H/tp query heads, KV/tp kv heads, same rep = H/KV), so
        # the kernel body and every index map run unchanged; mesh-off,
        # the local shapes ARE the global ones
        q_l, kp_l = ops[nscal + 2], ops[nscal + 3]
        Hl, KVl = q_l.shape[2], kp_l.shape[2]
        in_specs = [
            pl.BlockSpec((1, Pt), _tile_map),
            pl.BlockSpec((1, Pt), _tile_map),
            pl.BlockSpec((1, Pt, Hl, hd), _tile3_map),
            pl.BlockSpec((1, bs, KVl, hd), _kv_map),
            pl.BlockSpec((1, bs, KVl, hd), _kv_map),
        ]
        if suffix:
            in_specs += [
                pl.BlockSpec((1, S, KVl, hd), _suffix_map),
                pl.BlockSpec((1, S, KVl, hd), _suffix_map),
                pl.BlockSpec((1, Pt, S), _svis_map),
            ]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            # int8 pools prefetch the per-block dequant scales next to
            # the table/live-lengths so the kernel body reads from SMEM
            num_scalar_prefetch=nscal,
            # the suffix slab rides one extra chunk past the table
            # width — the pool block loop is untouched, the slab chunk
            # finalizes
            grid=(R, T, M + 1 if suffix else M),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, Pt, Hl, hd), _tile3_map),
            scratch_shapes=[
                pltpu.VMEM((Pt, Hl, hd), jnp.float32),
                pltpu.VMEM((Pt, Hl), jnp.float32),
                pltpu.VMEM((Pt, Hl), jnp.float32),
            ],
        )
        call = pl.pallas_call(
            functools.partial(_rpa_kernel, bs=bs,
                              scale=1.0 / math.sqrt(hd),
                              quantized=quantized, suffix=suffix,
                              nchunks=M),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((R, P, Hl, hd), q.dtype),
            interpret=interpret,
        )
        return call(*ops)

    if mesh is None:
        return _kernel_call(*args)
    size = mesh.shape[mesh_axis]
    if H % size or KV % size:
        raise ValueError(
            f"head counts (H={H}, KV={KV}) must divide the mesh axis "
            f"{mesh_axis!r} size {size} to shard the ragged kernel")
    from jax.experimental.shard_map import shard_map

    # check_rep=False: pallas_call has no replication rule; the specs
    # above are the ground truth
    in_specs, out_spec = _shard_specs(mesh_axis, quantized, suffix)
    return shard_map(_kernel_call, mesh=mesh, in_specs=in_specs,
                     out_specs=out_spec, check_rep=False)(*args)
