"""KV-cache autoregressive generation for the flagship Llama family.

Reference analog: PaddleNLP `llm/` predict recipes — model.generate() with
decode_strategy greedy_search/sampling over a fused-attention KV cache
(upstream-canonical, unverified — SURVEY.md §0; VERDICT r1 missing item
10: the inference Predictor had no decoder-cache story).

TPU-native design: the cache is a static-shape [L, B, T_max, KV, hd] pair
updated with dynamic_update_slice at a traced position; prefill and
per-token decode share ONE cached-attention path (prefill is the P>1
case); the decode loop is a lax.scan inside jit — no host round-trip per
token. Sampling (temperature / top-k / top-p) is branch-free masking over
logits, compiled into the same program.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..kernels.rms_norm import rms_norm_ref
from ..kernels.rope import rope_freqs, apply_rope_half
from . import llama


class KVCache(NamedTuple):
    """k/v: [L, B, T_max, KV_heads, head_dim] in the compute dtype."""
    k: jax.Array
    v: jax.Array


def cache_spec() -> P:
    """PartitionSpec for each KV-cache leaf [L, B, T, KV, hd]: batch over
    the data axes, KV heads over mp (tensor parallel) — the serving-side
    counterpart of llama.param_specs' head-dim column split. The cache
    never leaves its shard: decode writes ride dynamic_update_slice on the
    local [KV/mp] head block (reference: PaddleNLP llm/ predict's
    mp-sharded fused-attention cache; SURVEY.md §3.5)."""
    return P(None, ("dp", "sharding"), None, "mp", None)


def _constrain(x, mesh, spec):
    if mesh is None:
        return x
    return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def init_cache(cfg: llama.LlamaConfig, batch: int, max_len: int,
               mesh=None) -> KVCache:
    L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                 cfg.head_dim)
    shape = (L, batch, max_len, KV, hd)
    z = jnp.zeros(shape, cfg.dtype)
    return KVCache(_constrain(z, mesh, cache_spec()),
                   _constrain(z, mesh, cache_spec()))


# decode matmul weights eligible for weight-only quantization (order
# mirrors upstream PaddleNLP's weight_only serving list: every per-layer
# projection; embed/norms stay high-precision)
QUANT_KEYS = ("q_proj", "k_proj", "v_proj", "o_proj",
              "gate_proj", "up_proj", "down_proj")


def quantize_for_serving(params: Dict[str, Any], bits: int = 8,
                         quantize_head: bool = True) -> Dict[str, Any]:
    """Weight-only quantization of the decode matmul weights.

    Reference analog: PaddleNLP llm/ predict --quant_type weight_only_int8
    (upstream python/paddle/nn/quant/quantized_linear.py weight_quantize;
    SURVEY.md §3.5) — the serving default in the reference ecosystem.

    Each projection [L, Din, Dout] becomes int8 (or int4) codes plus a
    per-(layer, output-channel) f32 scale stored under '<name>:scale'
    ([L, 1, Dout] — abs-max over the contracted dim). forward_cached
    dequantizes in-register: XLA fuses convert*scale into the dot's
    operand read, so decode streams int codes from HBM and the
    weight-bandwidth roofline halves (int8) or quarters (int4).

    quantize_head also quantizes lm_head (skipped automatically for tied
    embeddings — the gather path wants the full-precision table)."""
    if bits == 8:
        bound, store = 127.0, jnp.int8
    elif bits == 4:
        bound, store = 7.0, jnp.int4
    else:
        raise ValueError(f"weight-only bits must be 8 or 4, got {bits}")

    def quant(w):
        w32 = jnp.asarray(w, jnp.float32)
        scale = jnp.maximum(jnp.max(jnp.abs(w32), axis=-2, keepdims=True),
                            1e-9) / bound
        codes = jnp.clip(jnp.round(w32 / scale), -bound, bound).astype(store)
        return codes, scale.astype(jnp.float32)

    out = dict(params)
    layers = dict(params["layers"])
    for name in QUANT_KEYS:
        codes, scale = quant(layers[name])
        layers[name] = codes
        layers[name + ":scale"] = scale
    out["layers"] = layers
    if quantize_head and "lm_head" in params:
        codes, scale = quant(params["lm_head"])
        out["lm_head"] = codes
        out["lm_head:scale"] = scale
    return out


def quantized_specs(specs: Dict[str, Any], params: Dict[str, Any]
                    ) -> Dict[str, Any]:
    """Extend a param-spec tree to a quantize_for_serving tree: each
    '<name>:scale' leaf takes the weight's spec with the contracted dim
    (size 1 in the scale) forced replicated — e.g. o_proj P(None,'mp',None)
    → scale P(None, None, None)."""
    out = dict(specs)
    lspecs = dict(specs["layers"])
    for name in QUANT_KEYS:
        if name + ":scale" in params["layers"]:
            s = list(lspecs[name])
            s[-2] = None
            lspecs[name + ":scale"] = P(*s)
    out["layers"] = lspecs
    if "lm_head:scale" in params and "lm_head" in specs:
        s = list(specs["lm_head"])
        s[-2] = None
        out["lm_head:scale"] = P(*s)
    return out


def _wq(tree, name, cd):
    """Read a possibly weight-only-quantized weight: dequantize-on-read
    (codes * scale fuses into the consuming dot's operand)."""
    scale = tree.get(name + ":scale")
    w = tree[name]
    if scale is not None:
        return w.astype(cd) * scale.astype(cd)
    return w.astype(cd)


def _mlp_cached(x, lp, cfg):
    """SwiGLU MLP over _wq reads (llama._mlp's serving twin — the train
    path never sees quantized weights)."""
    g = x @ _wq(lp, "gate_proj", cfg.dtype)
    u = x @ _wq(lp, "up_proj", cfg.dtype)
    return (jax.nn.silu(g) * u) @ _wq(lp, "down_proj", cfg.dtype)


def _final_head_cached(params, x, cfg):
    """Final RMSNorm + LM head with _wq on lm_head; tied-embedding (or
    unquantized) checkpoints fall through to llama's head."""
    if "lm_head:scale" not in params:
        return llama._final_head(params, x, cfg)
    cd = cfg.dtype
    x = rms_norm_ref(x, params["norm"], cfg.rms_norm_eps)
    return (x.astype(cd) @ _wq(params, "lm_head", cd)).astype(jnp.float32)


def _gqa_cached_attention(q, ck, cv, pos):
    """Cached-attention inner: q [B,P,H,hd] against THIS layer's cache
    ck/cv [B,T,KV,hd] with causal visibility at absolute position pos.
    Query heads are grouped per KV head (no jnp.repeat — the expansion
    rides the einsum's free dims); scores/softmax/probs stay f32 (probs
    are tiny next to the cache, and bf16-in/f32-accumulate dots make the
    result bit-identical to mha_ref's cast-to-f32 formulation)."""
    import math
    B, P, H, hd = q.shape
    T, KV = ck.shape[1], ck.shape[2]
    rep = H // KV
    qg = q.reshape(B, P, KV, rep, hd)
    s = jnp.einsum("bpkrd,btkd->bkrpt", qg, ck,
                   preferred_element_type=jnp.float32) / math.sqrt(hd)
    if P == 1:
        vis = (jnp.arange(T) <= pos)[None, None, None, None, :]
    else:
        # key j visible to query i (absolute pos+i) iff j <= pos+i
        vis = ((pos + jnp.arange(P)[:, None]) >= jnp.arange(T)[None, :]
               )[None, None, None]
    s = jnp.where(vis, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkrpt,btkd->bpkrd", p, cv,
                   preferred_element_type=jnp.float32)
    return o.reshape(B, P, H, hd)


def _attention_cached(x, lp, cfg, cos, sin, ck, cv, pos):
    """x: [B, P, D] new tokens at absolute positions pos..pos+P-1.
    ck/cv: THIS layer's cache [B, T, KV, hd]. Returns (out, ck, cv)."""
    B, P, D = x.shape
    H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    cd = cfg.dtype
    T = ck.shape[1]
    q = (x @ _wq(lp, "q_proj", cd)).reshape(B, P, H, hd)
    k = (x @ _wq(lp, "k_proj", cd)).reshape(B, P, KV, hd)
    v = (x @ _wq(lp, "v_proj", cd)).reshape(B, P, KV, hd)
    positions = pos + jnp.arange(P)[None, :]          # [1, P] broadcasts
    q, k = apply_rope_half(q, k, cos, sin,
                           jnp.broadcast_to(positions, (B, P)))
    z = jnp.int32(0)
    at = (z, jnp.asarray(pos, jnp.int32), z, z)
    ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), at)
    cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), at)

    from .. kernels import flash_attention as fa
    if (P > 1 and isinstance(pos, int) and pos == 0
            and getattr(cfg, "use_flash", True)):
        # prefill: the prompt attends only to itself (cache beyond P is
        # unwritten), so this is plain causal self-attention — run the
        # pad-to-block Pallas flash kernel over the NEW k/v instead of
        # mha_ref over the full cache with a materialized [P, T] mask
        # (VERDICT r3 missing 2: an 8k prompt built an 8192² mask per head
        # while the training stack ran the same shape as a flash kernel).
        # _flash_impl keeps the training path's gate + graceful fallback:
        # ineligible shapes get causal mha_ref over the prompt — still
        # O(P²), never the [P, T] masked-cache path.
        o = fa._flash_impl(q, k, v, True, None)
    else:
        # decode (and non-flash prefill): exact attention over the full
        # static cache, GQA-grouped — mha_ref here repeated K/V to H query
        # heads IN F32 (jnp.repeat + cast), which the r5 decode profile
        # measured as ~1.8 GB/step of broadcast traffic dwarfing the
        # weight reads; the grouped einsums keep the cache bf16 and
        # unexpanded with f32 accumulation only in the dots.
        o = _gqa_cached_attention(q, ck, cv, pos)
    o = o.astype(cd)
    return (o.reshape(B, P, H * hd) @ _wq(lp, "o_proj", cd)), ck, cv


def forward_cached(params: Dict[str, Any], tokens: jax.Array,
                   cache: KVCache, pos, cfg: llama.LlamaConfig, mesh=None):
    """tokens [B, P] at absolute positions pos..pos+P-1 → (logits [B,P,V]
    f32, cache'). P>1 = prefill; P=1 = decode step. pos may be traced.

    With a mesh, activations are constrained [B over (dp, sharding), heads
    over mp implicitly via the weight shards] and the cache keeps
    cache_spec() — TP decode stays local per shard except the row-parallel
    o_proj/down_proj all-reduces GSPMD inserts (SURVEY.md §2.3 TP row)."""
    cd = cfg.dtype
    T = cache.k.shape[2]
    x = jnp.take(params["embed_tokens"], tokens, axis=0).astype(cd)
    x = _constrain(x, mesh, P(("dp", "sharding"), None, None))
    cos, sin = rope_freqs(cfg.head_dim, T, cfg.rope_theta, jnp.float32)

    def body(carry, lp):
        # the FULL cache rides the carry and each layer dynamic-updates
        # its own [1, B, T, KV, hd] slab in place — returning per-layer
        # caches as stacked scan outputs (the r4 formulation) made the
        # decode loop's carry double-buffer the whole cache with real
        # copies every token (~1 ms/step on the 2B decode profile)
        x, ka, va, li = carry
        ck = lax.dynamic_slice_in_dim(ka, li, 1, 0)[0]
        cv = lax.dynamic_slice_in_dim(va, li, 1, 0)[0]
        h = rms_norm_ref(x, lp["input_layernorm"], cfg.rms_norm_eps)
        a, ck, cv = _attention_cached(h, lp, cfg, cos, sin, ck, cv, pos)
        ka = lax.dynamic_update_slice_in_dim(ka, ck[None], li, 0)
        va = lax.dynamic_update_slice_in_dim(va, cv[None], li, 0)
        x = x + a
        h = rms_norm_ref(x, lp["post_attention_layernorm"], cfg.rms_norm_eps)
        x = x + _mlp_cached(h, lp, cfg)
        x = _constrain(x, mesh, P(("dp", "sharding"), None, None))
        return (x, ka, va, li + 1), None

    (x, ck, cv, _), _ = lax.scan(
        body, (x, cache.k, cache.v, jnp.int32(0)), params["layers"])
    logits = _final_head_cached(params, x, cfg)
    return logits, KVCache(_constrain(ck, mesh, cache_spec()),
                           _constrain(cv, mesh, cache_spec()))


_TOPP_CANDIDATES = 4096


def _sample(logits, key, temperature: float, top_k: int, top_p: float,
            greedy: bool):
    """logits [B, V] → token ids [B]. Branch-free top-k/top-p masking.

    Filters apply sequentially like the reference's TopKProcess →
    TopPProcess: top-p renormalizes over the top-k SURVIVORS, and top_k is
    clamped to vocab_size. Both filters ride lax.top_k — pure top-p
    thresholds over a bounded candidate set (_TOPP_CANDIDATES, exact
    because the cumulative probabilities use the FULL-vocab softmax
    denominator) instead of an O(V log V) full sort (VERDICT r3 weak 5).
    Whenever the exact top-p set is LARGER than the candidate cap (flat
    distributions: high temperature and p near 1 on a big vocab), that
    row falls back to untruncated sampling — every exact-set token stays
    sampleable at the cost of re-admitting the <(1-top_p) tail mass;
    truncating at the cap instead could drop almost all requested mass."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    V = logits.shape[-1]
    sorted_l = None
    if top_k:
        k = min(int(top_k), V)
        sorted_l = lax.top_k(logits, k)[0]          # descending, [B, k]
        logits = jnp.where(logits < sorted_l[:, -1][:, None], -1e30, logits)
    if top_p < 1.0:
        if sorted_l is None:
            cand = lax.top_k(logits, min(_TOPP_CANDIDATES, V))[0]
            # exact head of the full-vocab cumulative distribution: the
            # denominator is logsumexp over ALL logits, not the candidates
            lse = jax.scipy.special.logsumexp(logits, axis=-1,
                                              keepdims=True)
            probs = jnp.exp(cand - lse)
        else:
            # masked-out entries are -1e30 → softmax weight 0, so softmax
            # over the k survivors equals the renormalized truncated
            # distribution
            cand = sorted_l
            probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set whose cumulative prob >= top_p; clamp keeps at
        # least the top token even at top_p == 0
        cutoff_idx = jnp.maximum(
            jnp.sum((cum - probs) < top_p, axis=-1) - 1, 0)
        cutoff = jnp.take_along_axis(cand, cutoff_idx[:, None], axis=-1)
        if sorted_l is None and cand.shape[-1] < V:
            cutoff = jnp.where(cum[:, -1:] >= top_p, cutoff, -jnp.inf)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(params: Dict[str, Any], input_ids: jax.Array,
             cfg: llama.LlamaConfig, max_new_tokens: int = 32,
             temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
             greedy: bool = True, eos_token_id: Optional[int] = None,
             pad_token_id: int = 0, key: Optional[jax.Array] = None,
             mesh=None) -> jax.Array:
    """Autoregressive generation: prefill + compiled decode scan.

    input_ids [B, P] int32 → [B, max_new_tokens] int32 (positions after an
    eos are pad_token_id). The decode loop is ONE lax.scan — paddle-shaped
    model.generate(decode_strategy='greedy_search'/'sampling') semantics
    without the reference's per-token host loop.

    With a mesh (and params placed per llama.infer_param_specs), the whole
    prefill + decode scan is TP/DP-sharded: the KV cache stays sharded
    over mp heads (cache_spec) for the full loop — the PaddleNLP llm/
    predict mp>1 serving path, compiled (SURVEY.md §3.5)."""
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    B, P = input_ids.shape
    T = P + max_new_tokens
    if key is None:
        key = jax.random.PRNGKey(0)

    cache = init_cache(cfg, B, T, mesh)
    logits, cache = forward_cached(params, input_ids, cache, 0, cfg, mesh)
    key, sub = jax.random.split(key)
    first = _sample(logits[:, -1], sub, temperature, top_k, top_p, greedy)
    done0 = (first == eos_token_id) if eos_token_id is not None else \
        jnp.zeros((B,), bool)

    def step(carry, _):
        tok, cache, pos, key, done = carry
        logits, cache = forward_cached(params, tok[:, None], cache, pos,
                                       cfg, mesh)
        key, sub = jax.random.split(key)
        nxt = _sample(logits[:, 0], sub, temperature, top_k, top_p, greedy)
        nxt = jnp.where(done, pad_token_id, nxt)
        if eos_token_id is not None:
            done = done | (nxt == eos_token_id)
        return (nxt, cache, pos + 1, key, done), nxt

    (_, _, _, _, _), rest = lax.scan(
        step, (first, cache, jnp.int32(P), key, done0),
        None, length=max_new_tokens - 1)
    return jnp.concatenate([first[:, None], rest.T.astype(jnp.int32)],
                           axis=1)
