"""paddle_tpu.nlp — flagship LLM model family (BASELINE configs 1/2/4).

Reference analog: the PaddleNLP model zoo the reference's training recipes use
(out-of-repo domain suite, SURVEY.md §1 Lx; upstream-canonical, unverified
§0). Here the flagship is a functional, scan-based Llama family designed for
GSPMD sharding (see llama.py), plus the sharded train step (train.py)."""
from . import llama, moe, train, ernie, generation  # noqa: F401
from .generation import KVCache, init_cache, forward_cached, generate  # noqa: F401
from .moe import MoeConfig  # noqa: F401
from .llama import LlamaConfig, init_params, forward, loss_fn, param_specs  # noqa: F401
from .train import TrainState, make_optimizer, make_train_step, init_state, state_specs  # noqa: F401
