"""Llama-family flagship model — the BASELINE 'Llama-3-8B (TP+DP)' workload.

Reference analog: the PaddleNLP `llm/` Llama recipes the reference's BASELINE
configs point at (out-of-repo, SURVEY.md §1 Lx row; upstream-canonical,
unverified — SURVEY.md §0). The reference builds Llama out of
ColumnParallelLinear/RowParallelLinear mpu layers + fused rope/rms_norm/flash
attention kernels and runs it under fleet hybrid parallelism.

TPU-native design (SURVEY.md §7 M5): a pure-functional transformer whose
params are one pytree; layers are STACKED (leading [L] dim) and the decoder
runs as one `lax.scan` over layer params — one XLA while-loop instead of L
unrolled blocks (compile time O(1) in depth, same MXU schedule). Parallelism
is not code: `param_specs`/`act_specs` return PartitionSpec trees for the
hybrid mesh axes (dp, sharding=FSDP/ZeRO-3, sep=context, mp=tensor) and GSPMD
partitions the one program — the reference's mpu layer zoo collapses into
these tables. Compute in bf16 on the MXU, params/master state in f32,
softmax/loss in f32.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..kernels.flash_attention import flash_attention_fwd
from ..kernels.rms_norm import rms_norm_ref, rms_norm_train
from ..kernels.rope import rope_freqs, apply_rope_half


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32       # < heads → GQA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16           # compute dtype (MXU)
    param_dtype: Any = jnp.float32      # storage dtype (master weights)
    remat: bool = True                  # jax.checkpoint each layer body
    use_flash: bool = True
    # loss path: True routes loss_fn through fused_head_ce (no [B,S,V] f32
    # materialization — frees ~6GB at the 2B bench shape). Default False:
    # the dense 2B single-chip bench measures ~6pt MFU SLOWER through the
    # chunked scan (r4, consistent with r3's chunked-vocab finding); the
    # MoE model uses the fused path unconditionally for the memory headroom.
    fused_ce: bool = False
    # attention schedule: "flash" (single-device / GSPMD-sharded), or the
    # context-parallel schedules over the sep mesh axis — "ring"
    # (ppermute KV rotation, SURVEY.md §2.3 CP row) / "ulysses" (all_to_all
    # head<->seq swap, SEP row). Ignored when mesh is None or sep == 1.
    attn_impl: str = "flash"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def tiny(**over) -> "LlamaConfig":
        """Test/dryrun-sized config."""
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=128)
        base.update(over)
        return LlamaConfig(**base)

    @staticmethod
    def llama3_8b(**over) -> "LlamaConfig":
        base = dict(vocab_size=128256, hidden_size=4096,
                    intermediate_size=14336, num_hidden_layers=32,
                    num_attention_heads=32, num_key_value_heads=8,
                    max_position_embeddings=8192, rope_theta=500000.0)
        base.update(over)
        return LlamaConfig(**base)


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: LlamaConfig) -> Dict[str, Any]:
    """Initialize the parameter pytree. Layer weights are stacked on a
    leading [L] axis for the scan. Init matches the reference recipes:
    normal(0, 0.02) for projections/embeddings, ones for norm scales."""
    D, F, V, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                  cfg.num_hidden_layers)
    H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    pd = cfg.param_dtype
    ks = jax.random.split(key, 8)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(pd)

    params = {
        "embed_tokens": norm(ks[0], (V, D)),
        "layers": {
            "input_layernorm": jnp.ones((L, D), pd),
            "q_proj": norm(ks[1], (L, D, H * hd)),
            "k_proj": norm(ks[2], (L, D, KV * hd)),
            "v_proj": norm(ks[3], (L, D, KV * hd)),
            "o_proj": norm(ks[4], (L, H * hd, D)),
            "post_attention_layernorm": jnp.ones((L, D), pd),
            "gate_proj": norm(ks[5], (L, D, F)),
            "up_proj": norm(ks[6], (L, D, F)),
            "down_proj": norm(ks[7], (L, F, D)),
        },
        "norm": jnp.ones((D,), pd),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm(jax.random.fold_in(key, 99), (D, V))
    return params


def param_specs(cfg: LlamaConfig, pp: bool = False) -> Dict[str, Any]:
    """PartitionSpec tree matching init_params. This table IS the reference's
    TP layer zoo + GroupSharded stage-3 (SURVEY.md §2.3 TP/sharding rows):
      mp       = Megatron TP: qkv/gate/up column-split, o/down row-split,
                 embeddings vocab-split (VocabParallelEmbedding).
      sharding = ZeRO-3/FSDP: the *other* matmul dim, so every big weight is
                 2D-sharded and all-gathers ride ICI.
    Layer stack dim [L]: unsharded when pp=False (it is scanned); sharded
    over 'pp' when pp=True — contiguous L/pp layer blocks per stage, which
    IS the pipeline stage partition (reference: PipelineLayer LayerDesc
    partition-by-layer, SURVEY.md §2.3 PP row)."""
    lspec = "pp" if pp else None
    return {
        "embed_tokens": P("mp", "sharding"),
        "layers": {
            "input_layernorm": P(lspec, None),
            "q_proj": P(lspec, "sharding", "mp"),
            "k_proj": P(lspec, "sharding", "mp"),
            "v_proj": P(lspec, "sharding", "mp"),
            "o_proj": P(lspec, "mp", "sharding"),
            "post_attention_layernorm": P(lspec, None),
            "gate_proj": P(lspec, "sharding", "mp"),
            "up_proj": P(lspec, "sharding", "mp"),
            "down_proj": P(lspec, "mp", "sharding"),
        },
        "norm": P(None),
        "lm_head": P("sharding", "mp"),
    } if not cfg.tie_word_embeddings else {
        "embed_tokens": P("mp", "sharding"),
        "layers": param_specs(
            dataclasses.replace(cfg, tie_word_embeddings=False), pp)["layers"],
        "norm": P(None),
    }


def infer_param_specs(cfg: LlamaConfig) -> Dict[str, Any]:
    """Serving-side PartitionSpec tree: Megatron TP ONLY (mp on the
    head/ffn dims; qkv/gate/up column-split, o/down row-split, lm_head
    vocab-split), everything else replicated. Unlike param_specs there is
    no ZeRO 'sharding' axis — weights must stay resident so decode steps
    insert no per-step param all-gathers (the reference's PaddleNLP llm/
    predict mp>1 layout; SURVEY.md §3.5, VERDICT r2 missing item 1)."""
    specs = {
        "embed_tokens": P(None, None),
        "layers": {
            "input_layernorm": P(None, None),
            "q_proj": P(None, None, "mp"),
            "k_proj": P(None, None, "mp"),
            "v_proj": P(None, None, "mp"),
            "o_proj": P(None, "mp", None),
            "post_attention_layernorm": P(None, None),
            "gate_proj": P(None, None, "mp"),
            "up_proj": P(None, None, "mp"),
            "down_proj": P(None, "mp", None),
        },
        "norm": P(None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "mp")
    return specs


def act_spec() -> P:
    """Activation sharding [B, S, D]: batch over (dp, sharding) — ZeRO data
    axes — and sequence over sep (context parallel). Megatron-SP falls out of
    GSPMD: XLA converts the surrounding collectives (SURVEY.md §2.3 SP row)."""
    return P(("dp", "sharding"), "sep", None)


def batch_spec() -> P:
    """Token batch [B, S]."""
    return P(("dp", "sharding"), "sep")


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _attention(x, lp, cfg: LlamaConfig, cos, sin, mesh=None):
    """x: [B,S,D] (compute dtype); lp: this layer's param slice."""
    B, S, D = x.shape
    H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    cd = cfg.dtype
    q = (x @ lp["q_proj"].astype(cd)).reshape(B, S, H, hd)
    k = (x @ lp["k_proj"].astype(cd)).reshape(B, S, KV, hd)
    v = (x @ lp["v_proj"].astype(cd)).reshape(B, S, KV, hd)
    q, k = apply_rope_half(q, k, cos, sin)
    if (cfg.attn_impl in ("ring", "ulysses") and mesh is not None
            and "sep" in mesh.axis_names and mesh.shape["sep"] > 1):
        from ..kernels.ring_attention import sep_attention
        o = sep_attention(q, k, v, mesh, impl=cfg.attn_impl, causal=True)
    elif cfg.use_flash:
        o = flash_attention_fwd(q, k, v, True, None)
    else:
        from .. kernels.flash_attention import mha_ref
        o = mha_ref(q, k, v, causal=True)
    o = o.reshape(B, S, H * hd)
    return o @ lp["o_proj"].astype(cd)


def _mlp(x, lp, cfg: LlamaConfig):
    cd = cfg.dtype
    g = x @ lp["gate_proj"].astype(cd)
    u = x @ lp["up_proj"].astype(cd)
    return (jax.nn.silu(g) * u) @ lp["down_proj"].astype(cd)


def _decoder_layer(x, lp, cfg: LlamaConfig, cos, sin, mesh=None):
    # fused-backward norm everywhere (XLA's autodiff of the ref emits
    # ~7x-slower backward fusions — the round-4 dense-2B profile's
    # largest non-GEMM cost): bare pallas_call on one chip, shard_mapped
    # over the activation shards under a mesh (r5 — previously the mesh
    # path dropped to jnp because pallas is opaque to GSPMD)
    norm = _make_norm(cfg, mesh)
    h = norm(x, lp["input_layernorm"])
    x = x + _attention(h, lp, cfg, cos, sin, mesh)
    h = norm(x, lp["post_attention_layernorm"])
    x = x + _mlp(h, lp, cfg)
    return x


def in_manual_axis(*names) -> bool:
    """True when tracing inside a shard_map MANUAL over any of `names`
    (e.g. the compiled-pipeline stage body, manual over 'pp') — a nested
    shard_map over the remaining auto axes is unsupported there, so the
    mesh-aware fused kernels must fall back to their jnp formulations."""
    for n in names:
        try:
            jax.lax.axis_index(n)
            return True
        # ptlint: disable=EXC001 — axis_index on an unbound axis raises a
        # jax-version-dependent type (NameError today); unbound IS the
        # probe result, not a failure
        except Exception:
            continue
    return False


def _make_norm(cfg: LlamaConfig, mesh):
    """RMSNorm closure: single-chip fused kernel, or the shard_mapped
    fused kernel over act_spec shards under a mesh (off-TPU meshes fall
    through to jnp inside the shard, as before). Inside a pipeline
    stage (manual over pp) the jnp path keeps GSPMD partitioning the
    remaining axes."""
    from ..kernels.flash_attention import _pallas_available
    from ..kernels.rms_norm import rms_norm_train_sharded
    if mesh is None:
        return lambda h, w: rms_norm_train(h, w, cfg.rms_norm_eps, True)
    if in_manual_axis("pp") or not _pallas_available():
        # CPU meshes keep the GLOBAL jnp formulation (bit-identical to
        # the mesh=None reference — shard_mapping the same math changes
        # bf16 fusion rounding enough to trip tight parity tests)
        return lambda h, w: rms_norm_train(h, w, cfg.rms_norm_eps, False)
    return lambda h, w: rms_norm_train_sharded(h, w, cfg.rms_norm_eps,
                                               mesh, act_spec())


def _backbone(params, tokens, cfg: LlamaConfig, mesh=None):
    """Embed + decoder stack → pre-norm hidden states [B, S, D].

    The decoder is one lax.scan over the stacked layer params; each body is
    optionally jax.checkpoint-ed (the reference's recompute_sequential,
    SURVEY.md §2.4 recompute row, as a remat policy instead of a PyLayer).
    With a mesh, activations carry sharding constraints (act_spec)."""
    cd = cfg.dtype
    x = jnp.take(params["embed_tokens"], tokens, axis=0).astype(cd)
    cos, sin = rope_freqs(cfg.head_dim, tokens.shape[1], cfg.rope_theta, jnp.float32)

    def maybe_constrain(h):
        if mesh is not None:
            from jax.sharding import NamedSharding
            h = jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh, act_spec()))
        return h

    x = maybe_constrain(x)

    def body(h, lp):
        h = _decoder_layer(h, lp, cfg, cos, sin, mesh)
        return maybe_constrain(h), None

    if cfg.remat:
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.nothing_saveable)
    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def forward(params: Dict[str, Any], tokens: jax.Array, cfg: LlamaConfig,
            mesh=None) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, V] (f32)."""
    return _final_head(params, _backbone(params, tokens, cfg, mesh), cfg)


def _head_weights(params, cfg: LlamaConfig):
    """The LM head matrix [D, V] — ONE selection point for the tied /
    untied choice (shared by the logits and fused-CE paths)."""
    return (params["embed_tokens"].T if cfg.tie_word_embeddings
            else params["lm_head"])


def _final_head(params, x, cfg: LlamaConfig):
    """Final RMSNorm + LM head: x [B,S,D] → logits [B,S,V] (f32)."""
    cd = cfg.dtype
    x = rms_norm_ref(x, params["norm"], cfg.rms_norm_eps)
    logits = x.astype(cd) @ _head_weights(params, cfg).astype(cd)
    return logits.astype(jnp.float32)


def forward_pp(params: Dict[str, Any], tokens: jax.Array, cfg: LlamaConfig,
               mesh, num_microbatches: int,
               virtual_pp: int = 1) -> jax.Array:
    """Pipeline-parallel forward: the decoder stack runs as a compiled GPipe
    schedule over the mesh's `pp` axis (parallel.pipeline), embed/head stay
    GSPMD (replicated compute over pp, sharded over mp/sharding).

    virtual_pp > 1 selects the interleaved (virtual-pp) circular schedule:
    each device holds virtual_pp non-contiguous layer chunks, shrinking the
    fill/drain bubble by that factor (reference: PipelineParallel's
    interleaved mode). Note the [v, p, L/(v*p)] chunk layout differs from
    param_specs' contiguous-P('pp') blocks, so GSPMD reshards the layer
    stack at entry — init with a matching sharding for production runs.

    Reference analog: PipelineParallel.train_batch's forward half
    (SURVEY.md §3.3) — here the microbatch loop is a lax.scan and the stage
    hops are ppermute, all inside one XLA program."""
    from ..parallel.pipeline import (interleaved, pipelined,
                                     stack_virtual_chunks)

    n, stage_params, stage_fn = _pp_stage_setup(
        params, tokens.shape, cfg, mesh, num_microbatches,
        need_stage_params=(virtual_pp == 1))
    B, S = tokens.shape
    M = num_microbatches
    x = jnp.take(params["embed_tokens"], tokens, axis=0).astype(cfg.dtype)
    mb = x.reshape((M, B // M) + x.shape[1:])
    if virtual_pp > 1:
        chunks = stack_virtual_chunks(
            params["layers"], n, virtual_pp, mesh=mesh)
        chunk_fn = interleaved(stage_fn, mesh, v=virtual_pp,
                               remat=cfg.remat)
        outs = chunk_fn(chunks, mb)
    else:
        outs = pipelined(stage_fn, mesh, remat=cfg.remat)(stage_params, mb)
    x = outs.reshape(B, S, -1)
    return _final_head(params, x, cfg)


def _pp_stage_setup(params, tokens_shape, cfg: LlamaConfig, mesh,
                    num_microbatches: int, need_stage_params: bool = True):
    """Shared pipeline-partition plumbing for the GPipe and 1F1B paths:
    validates divisibility, reshapes [L, ...] layer params into
    [n, L/n, ...] stage slices (a LOCAL no-op when layers are sharded
    P('pp') — contiguous blocks, i.e. param_specs(cfg, pp=True), the
    reference's LayerDesc partition-by-layer), and builds the stage body.
    Returns (n_stages, stage_params, stage_fn). The interleaved/virtual-pp
    callers pass need_stage_params=False — they build their own
    [v, p, L/(v·p)] chunk layout and must not pay this reshape (ADVICE r2)."""
    n = mesh.shape["pp"]
    B, S = tokens_shape
    if B % num_microbatches:
        raise ValueError(
            f"batch {B} not divisible by {num_microbatches} microbatches")
    L = cfg.num_hidden_layers
    if L % n:
        raise ValueError(
            f"{L} decoder layers not divisible by pp={n} stages")
    cos, sin = rope_freqs(cfg.head_dim, S, cfg.rope_theta, jnp.float32)
    stage_params = None
    if need_stage_params:
        stage_params = jax.tree.map(
            lambda p: p.reshape((n, L // n) + p.shape[1:]), params["layers"])

    def stage_fn(local_layers, h):
        def body(h, lp):
            return _decoder_layer(h, lp, cfg, cos, sin, mesh), None
        h, _ = jax.lax.scan(body, h, local_layers)
        return h

    return n, stage_params, stage_fn


def _mb_loss(logits, tokens):
    """Per-microbatch next-token loss — same normalization as loss_fn, so
    the mean over microbatches equals the global loss."""
    targets = jnp.roll(tokens, -1, axis=1)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    seq = tokens.shape[1]
    valid = (jnp.arange(seq) < seq - 1).astype(logits.dtype)
    return jnp.sum((logz - gold) * valid[None]) / (
        tokens.shape[0] * (seq - 1))


_CE_CHUNKS = 8


@jax.custom_vjp
def fused_head_ce(x, head, tokens):
    """LM head + next-token CE WITHOUT materializing [B, S, V] f32 logits.

    The straightforward `_final_head + _mb_loss` makes autodiff save the
    full f32 logits (4.2 GB at the bench shape) and the bwd rebuild a
    bf16 copy — ~100 ms/step of the MoE bench was this head/loss block
    (xplane profile, VERDICT r3 task 1). Here the forward scans S-chunks
    keeping only logsumexp + the gold logit (residuals [B, S] f32), and
    the backward recomputes each chunk's logits in bf16 and feeds
    (softmax − onehot) straight into the dx/dhead GEMMs. Chunking is over
    SEQUENCE — the vocab-chunked variant measured slower on the dense
    bench (r3 notes).

    x: post-RMSNorm activations [B, S, D] (compute dtype); head [D, V];
    tokens [B, S] int32. Returns the scalar mean loss."""
    loss, _ = _fused_head_ce_fwd(x, head, tokens)
    return loss


def _ce_scan_chunks(x, tokens):
    B, S, D = x.shape
    # largest chunk count <= _CE_CHUNKS dividing S — never silently fall
    # back to one chunk (nc=1 would materialize the full [B, S, V] f32
    # logits this function exists to avoid)
    nc = next(n for n in range(_CE_CHUNKS, 0, -1) if S % n == 0)
    c = S // nc
    xs = x.reshape(B, nc, c, D).swapaxes(0, 1)           # [nc, B, c, D]
    tg = jnp.roll(tokens, -1, axis=1).reshape(B, nc, c).swapaxes(0, 1)
    return xs, tg, nc, c


def _fused_head_ce_fwd(x, head, tokens):
    B, S, D = x.shape
    xs, tg, nc, c = _ce_scan_chunks(x, tokens)

    def chunk(_, xt):
        xc, tc = xt
        logits = (xc @ head).astype(jnp.float32)         # [B, c, V] transient
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        return None, (logz, gold)

    _, (logz, gold) = lax.scan(chunk, None, (xs, tg))
    logz = logz.swapaxes(0, 1).reshape(B, S)
    gold = gold.swapaxes(0, 1).reshape(B, S)
    valid = (jnp.arange(S) < S - 1).astype(jnp.float32)
    loss = jnp.sum((logz - gold) * valid[None]) / (B * (S - 1))
    return loss, (x, head, tokens, logz)


def _fused_head_ce_bwd(res, g):
    x, head, tokens, logz = res
    B, S, D = x.shape
    V = head.shape[1]
    xs, tg, nc, c = _ce_scan_chunks(x, tokens)
    lz = logz.reshape(B, nc, c).swapaxes(0, 1)
    valid = (jnp.arange(S) < S - 1).astype(jnp.float32).reshape(nc, 1, c)
    scale = g / (B * (S - 1))

    def chunk(dhead, args):
        xc, tc, lzc, vc = args
        logits = (xc @ head).astype(jnp.float32)
        p = jnp.exp(logits - lzc[..., None])
        d = p - jax.nn.one_hot(tc, V, dtype=jnp.float32)
        d = (d * (vc[..., None] * scale)).astype(x.dtype)   # [B, c, V]
        dx_c = d @ head.T
        dhead = dhead + jnp.einsum("bcd,bcv->dv", xc, d).astype(jnp.float32)
        return dhead, dx_c

    # dhead accumulates in f32: a bf16 carry saves ~17 ms/step of
    # convert_add traffic on the MoE bench but rounds per chunk — measured
    # only +0.08pt MFU, not worth the longer-seq gradient-precision risk
    dhead, dxs = lax.scan(
        chunk, jnp.zeros((D, V), jnp.float32),
        (xs, tg, lz, jnp.broadcast_to(valid, (nc, B, c))))
    dx = dxs.swapaxes(0, 1).reshape(B, S, D)
    return (dx, dhead.astype(head.dtype),
            _np.zeros(tokens.shape, jax.dtypes.float0))


fused_head_ce.defvjp(_fused_head_ce_fwd, _fused_head_ce_bwd)


def _head_ce(params, x, cfg: LlamaConfig, tokens):
    """Final norm + fused head/CE (the loss-path twin of _final_head)."""
    cd = cfg.dtype
    x = rms_norm_ref(x, params["norm"], cfg.rms_norm_eps)
    return fused_head_ce(x.astype(cd),
                         _head_weights(params, cfg).astype(cd), tokens)


def loss_and_grad_pp(params: Dict[str, Any], tokens: jax.Array,
                     cfg: LlamaConfig, mesh, num_microbatches: int,
                     virtual_pp: int = 1):
    """Fused loss + grads through the compiled 1F1B pipeline schedule.

    Reference analog: PipelineParallel.train_batch with its default 1F1B
    scheduler (fleet/meta_parallel/pipeline_parallel.py, SURVEY.md §3.3).
    Unlike the GPipe path (loss_fn + jax.grad, which transposes the forward
    scan and therefore keeps O(M) microbatch activations live), this runs
    parallel.pipeline.one_f_one_b: embedding at stage 0, decoder slices per
    stage, final norm + head + loss at the last stage, O(pp) activation
    residency. Returns (loss, grads) with grads matching the params tree.

    virtual_pp > 1 selects interleaved_one_f_one_b (the reference's
    interleaved/virtual-pp mode IS a 1F1B schedule): v layer chunks per
    device, bubble shrunk by v, activation residency O(v·pp) —
    still independent of num_microbatches (VERDICT r2 missing 2).
    """
    from ..parallel.pipeline import run_1f1b

    n, _, stage_fn = _pp_stage_setup(
        params, tokens.shape, cfg, mesh, num_microbatches,
        need_stage_params=False)
    B, S = tokens.shape
    M = num_microbatches
    L = cfg.num_hidden_layers
    cd = cfg.dtype
    first_params = params["embed_tokens"]
    last_params = {"norm": params["norm"]}
    if cfg.tie_word_embeddings:
        last_params["embed_tokens"] = params["embed_tokens"]
    else:
        last_params["lm_head"] = params["lm_head"]

    def first_fn(embed, tok_mb):
        return jnp.take(embed, tok_mb, axis=0).astype(cd)

    def last_fn(lp, y, tok_mb):
        x = rms_norm_ref(y, lp["norm"], cfg.rms_norm_eps)
        head = (lp["embed_tokens"].T if cfg.tie_word_embeddings
                else lp["lm_head"])
        logits = (x.astype(cd) @ head.astype(cd)).astype(jnp.float32)
        return _mb_loss(logits, tok_mb)

    toks_mb = tokens.reshape((M, B // M) + tokens.shape[1:])
    loss, g_layers, g_f, g_l = run_1f1b(
        stage_fn, first_fn, last_fn, mesh, params["layers"], first_params,
        last_params, toks_mb, n_stages=n, virtual_pp=virtual_pp)

    d_embed = g_f
    if cfg.tie_word_embeddings:
        d_embed = d_embed + g_l["embed_tokens"]
    grads = {
        "embed_tokens": d_embed,
        "layers": g_layers,
        "norm": g_l["norm"],
    }
    if not cfg.tie_word_embeddings:
        grads["lm_head"] = g_l["lm_head"]
    grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
    return loss, grads


def loss_fn(params, tokens, cfg: LlamaConfig, mesh=None,
            pp_microbatches: Optional[int] = None, pp_virtual: int = 1):
    """Next-token cross entropy, masked at the final position. f32 softmax.

    Shapes stay [B, S] throughout (targets via roll + mask, not slicing):
    S-1 is generally not divisible by the sep axis, and uneven seq sharding
    of the embedding-grad scatter aborts XLA's SPMD partitioner
    (PadBaseShapeBeforeUnevenTiledSharding CHECK) — beyond being slower.

    pp_microbatches: with a mesh whose pp axis > 1, run the decoder through
    the compiled GPipe schedule with this many microbatches."""
    if (pp_microbatches and mesh is not None
            and "pp" in mesh.axis_names and mesh.shape["pp"] > 1):
        logits = forward_pp(params, tokens, cfg, mesh, pp_microbatches,
                            pp_virtual)
        return _mb_loss(logits, tokens)
    if cfg.fused_ce:
        return _head_ce(params, _backbone(params, tokens, cfg, mesh), cfg,
                        tokens)
    return _mb_loss(forward(params, tokens, cfg, mesh), tokens)


def num_params(cfg: LlamaConfig) -> int:
    D, F, V, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                  cfg.num_hidden_layers)
    H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    per_layer = 2 * D + D * H * hd + 2 * D * KV * hd + H * hd * D + 3 * D * F
    total = V * D + L * per_layer + D
    if not cfg.tie_word_embeddings:
        total += D * V
    return total


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Approx. train FLOPs/token (fwd+bwd = 6·params_matmul + attention)."""
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    # vocab term: only the OUTPUT projection is a matmul (the input
    # embedding is a gather — ~zero MXU FLOPs, tied or not)
    matmul = L * (D * (H + 2 * KV) * hd + H * hd * D + 3 * D * F) \
        + cfg.vocab_size * D
    # causal attention MACs/token: QK^T + PV visit ~seq/2 keys each →
    # 2 * H*hd*seq/2 = H*hd*seq (the flash kernels really skip the masked
    # half, so crediting full attention would overstate MFU)
    attn = L * H * hd * seq_len
    return 6.0 * (matmul + attn)
