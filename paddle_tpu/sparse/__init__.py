"""paddle.sparse — COO/CSR tensors and ops over jax.experimental.sparse.

Reference parity: python/paddle/sparse/ + paddle/phi/kernels/sparse/
(SparseCooTensor/SparseCsrTensor and the sparse op zoo — upstream-canonical,
unverified, SURVEY.md §0, §2.1 sparse row, §2.4).

TPU-native design: BCOO/BCSR are XLA-compilable sparse formats;
`matmul` lowers to bcoo_dot_general (the hot path — sparse×dense on the
MXU); elementwise ops run on the values buffer; binary sparse⊕sparse ops
densify (the reference's CUDA pairwise-merge kernels have no XLA analog
worth hand-writing at v1 scale).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..ops._registry import eager

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_sparse", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "relu", "abs", "sin", "tanh",
    "sqrt", "pow", "neg", "cast", "transpose", "sum", "nn",
]


class SparseCooTensor:
    """COO sparse tensor (phi::SparseCooTensor analog) over BCOO."""

    def __init__(self, bcoo: jsparse.BCOO, stop_gradient: bool = True):
        self._bcoo = bcoo
        self.stop_gradient = stop_gradient

    # -- paddle surface -----------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return np.dtype(self._bcoo.dtype)

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)  # paddle: [sparse_dim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates(),
                               self.stop_gradient)

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(
            self._bcoo.sum_duplicates()), self.stop_gradient)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor over BCSR."""

    def __init__(self, bcsr: jsparse.BCSR, stop_gradient: bool = True):
        self._bcsr = bcsr
        self.stop_gradient = stop_gradient

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return np.dtype(self._bcsr.dtype)

    @property
    def nnz(self) -> int:
        return int(self._bcsr.nse)

    def crows(self) -> Tensor:
        return Tensor(self._bcsr.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._bcsr.indices)

    def values(self) -> Tensor:
        return Tensor(self._bcsr.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcsr.todense())

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        return SparseCooTensor(self._bcsr.to_bcoo(), self.stop_gradient)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    """indices: [sparse_dim, nnz] (paddle layout); values: [nnz, ...]."""
    idx = jnp.asarray(indices._data if isinstance(indices, Tensor)
                      else indices, jnp.int32)
    val = jnp.asarray(values._data if isinstance(values, Tensor) else values)
    if dtype is not None:
        val = val.astype(dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1)))
        shape += val.shape[1:]
    bcoo = jsparse.BCOO((val, idx.T), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True) -> SparseCsrTensor:
    """Build a CSR sparse tensor from (crows, cols, values) index
    arrays and a dense shape (jax BCSR-backed)."""
    indptr = jnp.asarray(crows._data if isinstance(crows, Tensor) else crows,
                         jnp.int32)
    indices = jnp.asarray(cols._data if isinstance(cols, Tensor) else cols,
                          jnp.int32)
    val = jnp.asarray(values._data if isinstance(values, Tensor) else values)
    if dtype is not None:
        val = val.astype(dtype)
    bcsr = jsparse.BCSR((val, indices, indptr),
                        shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(bcsr, stop_gradient)


def is_sparse(x) -> bool:
    """True when `x` is a sparse (COO or CSR) tensor."""
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def _coo(x) -> jsparse.BCOO:
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x._bcsr.to_bcoo()
    raise TypeError(f"expected a sparse tensor, got {type(x)}")


def _dense(x):
    if isinstance(x, Tensor):
        return x._data
    if is_sparse(x):
        return _coo(x).todense()
    return jnp.asarray(x)


def _unary(x, fn) -> SparseCooTensor:
    """Elementwise op that preserves zeros → apply to values only."""
    b = _coo(x)
    return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices),
                                        shape=b.shape), x.stop_gradient)


def relu(x):
    """Elementwise max(x, 0) on the stored values (zeros preserved)."""
    return _unary(x, lambda v: jnp.maximum(v, 0))


def abs(x):
    """Elementwise absolute value on the stored values."""
    return _unary(x, jnp.abs)


def sin(x):
    """Elementwise sine on the stored values (zeros preserved)."""
    return _unary(x, jnp.sin)


def tanh(x):
    """Elementwise tanh on the stored values (zeros preserved)."""
    return _unary(x, jnp.tanh)


def sqrt(x):
    """Elementwise square root on the stored values."""
    return _unary(x, jnp.sqrt)


def neg(x):
    """Elementwise negation on the stored values."""
    return _unary(x, jnp.negative)


def pow(x, factor):
    """Elementwise power x**factor on the stored values."""
    return _unary(x, lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None):
    """Cast a COO tensor's index and/or value dtypes."""
    b = _coo(x)
    data = b.data if value_dtype is None else b.data.astype(value_dtype)
    idx = b.indices if index_dtype is None else b.indices.astype(index_dtype)
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=b.shape),
                           x.stop_gradient)


def transpose(x, perm):
    """Permute a sparse tensor's dimensions by `perm`."""
    b = _coo(x)
    return SparseCooTensor(b.transpose(tuple(perm)), x.stop_gradient)


def sum(x, axis=None, dtype=None, keepdim=False):
    """Sum a sparse tensor's values (all or along `axis`) into a
    dense Tensor."""
    b = _coo(x)
    out = b.sum() if axis is None else b.sum(axis)
    out = getattr(out, "todense", lambda: out)()
    out = jnp.asarray(out)
    if dtype is not None:
        out = out.astype(dtype)
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, axis)
    return Tensor(out)


def _binary_densify(x, y, fn):
    out = fn(_dense(x), _dense(y))
    # off-pattern artifacts (0/0 → NaN in divide) are zeros, not values
    out = jnp.where(jnp.isnan(out) | jnp.isinf(out), 0.0, out)
    nz = jnp.nonzero(out)  # dense result back to COO (v1 semantics)
    idx = jnp.stack(nz, axis=1)
    return SparseCooTensor(
        jsparse.BCOO((out[nz], idx), shape=out.shape))


def add(x, y):
    """Elementwise sum: sparse+sparse stays sparse (indices merged);
    any dense operand densifies."""
    if is_sparse(x) and is_sparse(y):
        bx, by = _coo(x), _coo(y)
        merged = jsparse.BCOO(
            (jnp.concatenate([bx.data, by.data]),
             jnp.concatenate([bx.indices, by.indices])),
            shape=bx.shape).sum_duplicates()
        return SparseCooTensor(merged)
    return Tensor(_dense(x) + _dense(y))


def subtract(x, y):
    """Elementwise difference (sparse-sparse stays sparse)."""
    if is_sparse(x) and is_sparse(y):
        return add(x, neg(y))
    return Tensor(_dense(x) - _dense(y))


def multiply(x, y):
    """Elementwise product (densified, re-sparsified from nonzeros)."""
    return _binary_densify(x, y, jnp.multiply)


def divide(x, y):
    """Elementwise quotient; 0/0 and x/0 artifacts drop to zeros."""
    return _binary_densify(x, y, jnp.divide)


def matmul(x, y):
    """sparse @ dense (the hot op — bcoo_dot_general on the MXU) or
    sparse @ sparse (densified result)."""
    if is_sparse(x) and not is_sparse(y):
        yd = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        out = jsparse.bcoo_dot_general(
            _coo(x), yd,
            dimension_numbers=(((len(x.shape) - 1,), (0,)), ((), ())))
        return Tensor(out)
    return Tensor(jnp.matmul(_dense(x), _dense(y)))


def masked_matmul(x, y, mask):
    """dense @ dense sampled at mask's sparsity pattern (SDDMM)."""
    xd, yd = _dense(x), _dense(y)
    b = _coo(mask)
    rows, cols = b.indices[:, 0], b.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xd[rows], yd.T[cols])
    return SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=b.shape))


class _SparseNN:
    """paddle.sparse.nn — layer-shaped wrappers."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    class Softmax:
        def __init__(self, axis=-1):
            self.axis = axis

        def __call__(self, x):
            # softmax over the last dense axis of each row's nonzeros:
            # densify with -inf fill (v1 semantics)
            d = _dense(x)
            filled = jnp.where(d == 0, -jnp.inf, d)
            out = jax.nn.softmax(filled, axis=self.axis)
            out = jnp.where(jnp.isnan(out) | (d == 0), 0.0, out)
            nz = jnp.nonzero(d)
            idx = jnp.stack(nz, axis=1)
            return SparseCooTensor(
                jsparse.BCOO((out[nz], idx), shape=out.shape))




# ---------------------------------------------------------------------------
# Round-3 breadth: the rest of the paddle.sparse unary zoo + utilities
# (python/paddle/sparse/unary.py — each is a values-buffer map; SURVEY.md
# §2.4 sparse row)
# ---------------------------------------------------------------------------

def asin(x):
    """Elementwise arcsine over the stored values (paddle.sparse.asin)."""
    return _unary(x, jnp.arcsin)


def atan(x):
    """Elementwise arctangent over the stored values (paddle.sparse.atan)."""
    return _unary(x, jnp.arctan)


def asinh(x):
    """Elementwise inverse hyperbolic sine over the stored values."""
    return _unary(x, jnp.arcsinh)


def atanh(x):
    """Elementwise inverse hyperbolic tangent over the stored values."""
    return _unary(x, jnp.arctanh)


def sinh(x):
    """Elementwise hyperbolic sine over the stored values."""
    return _unary(x, jnp.sinh)


def expm1(x):
    """Elementwise exp(x)-1 over the stored values (paddle.sparse.expm1)."""
    return _unary(x, jnp.expm1)


def log1p(x):
    """Elementwise log(1+x) over the stored values (paddle.sparse.log1p)."""
    return _unary(x, jnp.log1p)


def square(x):
    """Elementwise square over the stored values (paddle.sparse.square)."""
    return _unary(x, jnp.square)


def deg2rad(x):
    """Degrees-to-radians over the stored values (paddle.sparse.deg2rad)."""
    return _unary(x, jnp.deg2rad)


def rad2deg(x):
    """Radians-to-degrees over the stored values (paddle.sparse.rad2deg)."""
    return _unary(x, jnp.rad2deg)


def coalesce(x):
    """Sum duplicate indices into one entry per coordinate (COO canonical form)."""
    return x.coalesce()


def is_same_shape(x, y):
    """True when x and y have identical dense shapes (paddle.sparse.is_same_shape)."""
    return list(x.shape) == list(y.shape)


def mask_as(x, mask):
    """Keep x's entries at mask's sparsity pattern (paddle.sparse.mask_as):
    gather dense x at the mask's indices."""
    dense = x._data if isinstance(x, Tensor) else jnp.asarray(
        _dense(x) if isinstance(x, (SparseCooTensor, SparseCsrTensor))
        else x)
    m = mask if isinstance(mask, SparseCooTensor) else mask.to_sparse_coo() \
        if hasattr(mask, "to_sparse_coo") else mask
    idx = m._bcoo.indices
    vals = dense[tuple(idx[:, i] for i in range(idx.shape[1]))]
    return SparseCooTensor(
        jsparse.BCOO((vals.astype(dense.dtype), idx), shape=dense.shape),
        getattr(x, "stop_gradient", True))


def softmax(x, axis=-1):
    """Sparse softmax over the stored entries of each row (CSR/COO 2D)."""
    coo = x if isinstance(x, SparseCooTensor) else SparseCooTensor(
        x._bcsr.to_bcoo() if hasattr(x, "_bcsr") else x._bcoo)
    dense = coo._bcoo.todense()
    filled = coo._bcoo.todense() != 0
    z = jnp.where(filled, dense.astype(jnp.float32), -1e30)
    out = jax.nn.softmax(z, axis=axis)
    out = jnp.where(filled, out, 0.0)
    return SparseCooTensor(jsparse.BCOO.fromdense(out.astype(dense.dtype)),
                           coo.stop_gradient)


def slice(x, axes, starts, ends):
    """paddle.sparse.slice (shadows the builtin inside this module, like
    the reference's paddle.sparse.slice)."""
    import builtins
    d = _dense(x)
    slicer = [builtins.slice(None)] * d.ndim
    for a, s, e in zip(axes, starts, ends):
        slicer[a] = builtins.slice(s, e)
    out = d[tuple(slicer)]
    return SparseCooTensor(jsparse.BCOO.fromdense(out),
                           getattr(x, "stop_gradient", True))


def pca_lowrank(*a, **k):
    raise NotImplementedError(
        "paddle.sparse.pca_lowrank: use paddle.linalg.pca_lowrank on the "
        "densified tensor (paddle_tpu/sparse/__init__.py)")


def add_coo_coo(x, y):
    """COO + COO elementwise add — alias of `add` kept for the paddle kernel-named surface."""
    return add(x, y)


def add_coo_dense(x, y):
    """COO + dense elementwise add — alias of `add` kept for the paddle kernel-named surface."""
    return add(x, y)


def matmul_coo_dense(x, y):
    """COO x dense matmul — alias of `matmul` kept for the paddle kernel-named surface."""
    return matmul(x, y)


def matmul_csr_dense(x, y):
    """CSR x dense matmul — alias of `matmul` kept for the paddle kernel-named surface."""
    return matmul(x, y)


__all__ += ["asin", "atan", "asinh", "atanh", "sinh", "expm1", "log1p",
            "square", "deg2rad", "rad2deg", "coalesce", "is_same_shape",
            "mask_as", "softmax", "slice", "add_coo_coo", "add_coo_dense",
            "matmul_coo_dense", "matmul_csr_dense"]


from . import nn as nn  # noqa: E402  (real sparse.nn module, round 3)
