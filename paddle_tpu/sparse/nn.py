"""paddle.sparse.nn — the sparse layer zoo + functional.

Reference analog: python/paddle/sparse/nn/ (Conv3D/SubmConv3D/BatchNorm/
activation layers over SparseCooTensor — upstream-canonical, unverified,
SURVEY.md §0, §2.4 sparse row). TPU-native v1: submanifold/spatial sparse
conv densify through jax.lax.conv (XLA has no gather-scatter sparse conv;
the densified form is exact, just not memory-sparse), elementwise layers
map the values buffer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from . import (SparseCooTensor, SparseCsrTensor, _dense, _unary, relu as
               _relu_fn, softmax as _softmax_fn)
from ..nn.layer import Layer


# -- functional -------------------------------------------------------------

def relu(x):
    return _relu_fn(x)


def relu6(x):
    return _unary(x, lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01):
    return _unary(x, lambda v: jnp.where(v >= 0, v, negative_slope * v))


def softmax(x, axis=-1):
    return _softmax_fn(x, axis)


def _to_dense_ndhwc(x):
    return _dense(x)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC"):
    """Sparse conv3d (densified): x SparseCooTensor [N,D,H,W,C]."""
    d = _to_dense_ndhwc(x)
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    dil = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, int):
        pad = [(padding, padding)] * 3
    else:
        pad = [(p, p) for p in padding]
    out = jax.lax.conv_general_dilated(
        d.astype(jnp.float32), jnp.asarray(weight, jnp.float32),
        window_strides=s, padding=pad, rhs_dilation=dil,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        feature_group_count=groups)
    if bias is not None:
        out = out + jnp.asarray(bias, jnp.float32)
    return SparseCooTensor(jsparse.BCOO.fromdense(out.astype(d.dtype)))


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None):
    """Submanifold conv3d: conv, then mask outputs to the INPUT's active
    sites (the defining property of submanifold convolution)."""
    y = conv3d(x, weight, bias, stride, padding, dilation, groups,
               data_format)
    if list(y.shape[:-1]) != list(x.shape[:-1]):  # spatial dims must match
        return y
    active = _to_dense_ndhwc(x) != 0
    active = jnp.any(active, axis=-1, keepdims=True)
    masked = jnp.where(active, _dense(y), 0)
    return SparseCooTensor(jsparse.BCOO.fromdense(masked))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC"):
    """Sparse conv2d (densified): x SparseCooTensor [N,H,W,C] — the 2-D
    member of the upstream sparse conv family (paddle.sparse.nn.Conv2D)."""
    d = _to_dense_ndhwc(x)
    s = (stride,) * 2 if isinstance(stride, int) else tuple(stride)
    dil = (dilation,) * 2 if isinstance(dilation, int) else tuple(dilation)
    pad = ([(padding, padding)] * 2 if isinstance(padding, int)
           else [(p, p) for p in padding])
    out = jax.lax.conv_general_dilated(
        d.astype(jnp.float32), jnp.asarray(weight, jnp.float32),
        window_strides=s, padding=pad, rhs_dilation=dil,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups)
    if bias is not None:
        out = out + jnp.asarray(bias, jnp.float32)
    return SparseCooTensor(jsparse.BCOO.fromdense(out.astype(d.dtype)))


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None):
    """Submanifold conv2d: conv, then mask to the input's active sites."""
    y = conv2d(x, weight, bias, stride, padding, dilation, groups,
               data_format)
    if list(y.shape[:-1]) != list(x.shape[:-1]):
        return y
    active = jnp.any(_to_dense_ndhwc(x) != 0, axis=-1, keepdims=True)
    return SparseCooTensor(jsparse.BCOO.fromdense(
        jnp.where(active, _to_dense_ndhwc(y), 0)))


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC"):
    d = _to_dense_ndhwc(x)
    k = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(
        kernel_size)
    s = tuple(k) if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pad = ((0, 0),) + tuple(
        (padding, padding) if isinstance(padding, int) else (p, p)
        for p in ((padding,) * 3 if isinstance(padding, int) else padding)
    ) + ((0, 0),)
    out = jax.lax.reduce_window(
        d.astype(jnp.float32), -jnp.inf, jax.lax.max,
        (1,) + k + (1,), (1,) + s + (1,), pad)
    out = jnp.where(jnp.isinf(out), 0.0, out)
    return SparseCooTensor(jsparse.BCOO.fromdense(out.astype(d.dtype)))


def attention(query, key, value, sparse_mask=None, key_padding_mask=None,
              attn_mask=None, name=None):
    """paddle.sparse.nn.functional.attention: dense QK^T softmax V over the
    sparse_mask's pattern (densified v1)."""
    q = query._data if isinstance(query, Tensor) else jnp.asarray(query)
    k = key._data if isinstance(key, Tensor) else jnp.asarray(key)
    v = value._data if isinstance(value, Tensor) else jnp.asarray(value)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if sparse_mask is not None:
        pattern = _dense(sparse_mask) != 0
        s = jnp.where(pattern, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return Tensor(jnp.einsum("...qk,...kd->...qd", p,
                             v.astype(jnp.float32)).astype(q.dtype))


# -- layers -----------------------------------------------------------------

class ReLU(Layer):
    def forward(self, x):
        return relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return softmax(x, self._axis)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, data_format)

    def forward(self, x):
        return max_pool3d(x, *self._a)


class Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(
            kernel_size)
        self.weight = self.create_parameter(
            list(k) + [in_channels // groups, out_channels])
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True)
        self._a = (stride, padding, dilation, groups, data_format)

    def forward(self, x):
        s, p, d, g, df = self._a
        return conv3d(x, self.weight._data,
                      None if self.bias is None else self.bias._data,
                      s, p, d, g, df)


class SubmConv3D(Conv3D):
    def forward(self, x):
        s, p, d, g, df = self._a
        return subm_conv3d(x, self.weight._data,
                           None if self.bias is None else self.bias._data,
                           s, p, d, g, df)


class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__()
        k = (kernel_size,) * 2 if isinstance(kernel_size, int) else tuple(
            kernel_size)
        self.weight = self.create_parameter(
            list(k) + [in_channels // groups, out_channels])
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True)
        self._a = (stride, padding, dilation, groups, data_format)

    def forward(self, x):
        s, p, d, g, df = self._a
        return conv2d(x, self.weight._data,
                      None if self.bias is None else self.bias._data,
                      s, p, d, g, df)


class SubmConv2D(Conv2D):
    def forward(self, x):
        s, p, d, g, df = self._a
        return subm_conv2d(x, self.weight._data,
                           None if self.bias is None else self.bias._data,
                           s, p, d, g, df)


class BatchNorm(Layer):
    """Sparse BatchNorm: normalizes the values buffer over active sites."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn import initializer as I
        self.weight = self.create_parameter(
            [num_features], default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], default_initializer=I.Constant(0.0))
        self._eps = epsilon

    def forward(self, x):
        vals = x._bcoo.data.astype(jnp.float32)
        mean = jnp.mean(vals, axis=0)
        var = jnp.var(vals, axis=0)
        out = (vals - mean) / jnp.sqrt(var + self._eps)
        out = out * self.weight._data + self.bias._data
        return SparseCooTensor(jsparse.BCOO(
            (out.astype(x._bcoo.data.dtype), x._bcoo.indices),
            shape=x._bcoo.shape), x.stop_gradient)


SyncBatchNorm = BatchNorm
# dimension-suffixed aliases (upstream exposes BatchNorm under these
# names in examples/configs; the sparse values-buffer normalization is
# rank-agnostic)
BatchNorm1D = BatchNorm2D = BatchNorm3D = BatchNorm


class _FuncNS:
    relu = staticmethod(relu)
    relu6 = staticmethod(relu6)
    leaky_relu = staticmethod(leaky_relu)
    softmax = staticmethod(softmax)
    conv3d = staticmethod(conv3d)
    subm_conv3d = staticmethod(subm_conv3d)
    conv2d = staticmethod(conv2d)
    subm_conv2d = staticmethod(subm_conv2d)
    max_pool3d = staticmethod(max_pool3d)
    attention = staticmethod(attention)


functional = _FuncNS()
