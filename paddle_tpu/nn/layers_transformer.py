"""Transformer layers — parity with python/paddle/nn/layer/transformer.py
(upstream-canonical, unverified — SURVEY.md §0).

TPU-native: attention routes through the flash-attention entry
(paddle_tpu.kernels.flash_attention) so the whole block is MXU matmuls +
one fused softmax; everything jit-fuses into a single XLA computation."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from .layer import Layer
from .layers_common import Linear, Dropout, LayerList
from .layers_conv import LayerNorm
from . import functional as F


class MultiHeadAttention(Layer):
    """Paddle layout: query [B, S, E]; internal heads [B, S, H, D]."""

    Cache = tuple
    StaticCache = tuple

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.need_weights = need_weights
        self.dropout = dropout
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split(self, x):
        b, s = x.shape[0], x.shape[1]
        return x.reshape([b, s, self.num_heads, self.head_dim])

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._split(self.q_proj(query))
        k = self._split(self.k_proj(key))
        v = self._split(self.v_proj(value))
        if cache is not None:
            pk, pv = cache
            from ..ops.manipulation import concat
            k = concat([pk, k], axis=1)
            v = concat([pv, v], axis=1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout if self.training else 0.0)
        b, s = out.shape[0], out.shape[1]
        out = out.reshape([b, s, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out

    def gen_cache(self, key, value=None, type=None):
        from ..ops.creation import zeros
        b = key.shape[0]
        return (zeros([b, 0, self.num_heads, self.head_dim], dtype=key.dtype),
                zeros([b, 0, self.num_heads, self.head_dim], dtype=key.dtype))


def _get_activation(name):
    return {"relu": F.relu, "gelu": F.gelu}[name]


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = _get_activation(activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, attn_mask=src_mask)
        else:
            src, cache = self.self_attn(src, src, src, attn_mask=src_mask,
                                        cache=cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            _clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = _get_activation(activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            self.encoder = TransformerEncoder(
                enc_layer, num_encoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            self.decoder = TransformerDecoder(
                dec_layer, num_decoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ..ops.creation import to_tensor
        m = np.triu(np.full((length, length), -np.inf, dtype=np.float32), k=1)
        return to_tensor(m)


def _clone_layer(layer):
    """Fresh copy with newly-initialized parameters (paddle re-creates rather
    than sharing when stacking encoder layers)."""
    import copy

    new = copy.deepcopy(layer)
    # re-draw parameters so the stack isn't weight-tied
    for (_, p_new), (_, p_old) in zip(new.named_parameters(),
                                      layer.named_parameters()):
        if p_old.size > 1:
            from . import initializer as I
            p_new.set_value(I.XavierUniform()(tuple(p_old._data.shape),
                                              p_old.dtype))
    return new
