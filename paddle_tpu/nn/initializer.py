"""Parameter initializers — python/paddle/nn/initializer/ parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import random as prandom


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *k] (paddle OIHW)
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtypes.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        return (jax.random.normal(prandom.next_key(), shape, dtype=jnp.float32)
                * self.std + self.mean).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        r = jax.random.truncated_normal(prandom.next_key(), -2.0, 2.0, shape,
                                        dtype=jnp.float32)
        return (r * self.std + self.mean).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        return jax.random.uniform(prandom.next_key(), shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(d)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.slope ** 2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.slope ** 2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = np.asarray(getattr(self.value, "numpy", lambda: self.value)())
        return jnp.asarray(v, dtype=dtypes.convert_dtype(dtype)).reshape(shape)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        k_center = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            out[(i, i % ic) + k_center] = 1.0
        return jnp.asarray(out, dtype=dtypes.convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        r = jax.random.orthogonal(prandom.next_key(), shape[0],
                                  shape=()) if len(shape) == 1 else None
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        n = max(rows, cols)
        q = jax.random.orthogonal(prandom.next_key(), n)
        q = q[:rows, :cols] * self.gain
        return q.reshape(shape).astype(dtypes.convert_dtype(dtype))


# paddle spells these with set_global_initializer-style aliases too
def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """paddle.nn.initializer.set_global_initializer parity: default
    initializers used by create_parameter when no attr/default is given."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _global_default(is_bias):
    return _global_bias_init if is_bias else _global_weight_init
