"""paddle_tpu.nn — layer zoo + functional.

Reference parity: python/paddle/nn/ (~200 Layer classes — upstream-canonical,
unverified, SURVEY.md §0)."""
from .layer import Layer, ParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401
from . import functional as F  # noqa: F401

from .layers_common import (  # noqa: F401
    Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    FeatureAlphaDropout,
    Flatten, Unflatten, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    PixelShuffle, PixelUnshuffle, ChannelShuffle, Pad1D, Pad2D, Pad3D,
    ZeroPad2D, ZeroPad1D, ZeroPad3D, CosineSimilarity, PairwiseDistance,
    Sequential, LayerList, ParameterList, LayerDict, Bilinear, Fold, Unfold,
)
from .layers_conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm, MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    LPPool1D, LPPool2D, FractionalMaxPool2D, FractionalMaxPool3D,
)
from .layers_act_loss import (  # noqa: F401
    ReLU, ReLU6, GELU, SiLU, Silu, Swish, ELU, SELU, CELU, LeakyReLU,
    Hardshrink, Softshrink, Tanhshrink, Hardtanh, Hardsigmoid, Hardswish,
    Mish, Softplus, Softsign, LogSigmoid, Tanh, Sigmoid, LogSoftmax, Softmax,
    Softmax2D, Maxout, PReLU, ThresholdedReLU, RReLU, GLU,
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, TripletMarginLoss,
    TripletMarginWithDistanceLoss, CosineEmbeddingLoss, HingeEmbeddingLoss,
    HuberLoss, SoftMarginLoss, MultiLabelSoftMarginLoss, MultiMarginLoss,
    PoissonNLLLoss, GaussianNLLLoss, CTCLoss, RNNTLoss, AdaptiveLogSoftmaxWithLoss,
    HSigmoidLoss, GumbelSoftmax,
)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
# grad-clip classes live in paddle.nn too (reference re-export)
from ..optimizer.optimizers import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue)
from .layers_transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layers_rnn import (  # noqa: F401
    SimpleRNNCell, LSTMCell, GRUCell, SimpleRNN, LSTM, GRU, RNN, BiRNN,
    RNNCellBase,
)

from ..ops._registry import adopt_inplace as _  # noqa: F401  (import check)


def utils_clip_grad_norm_(parameters, max_norm, norm_type=2.0):
    """paddle.nn.utils.clip_grad_norm_ parity."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor

    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros((), dtype=jnp.float32))
    total = jnp.sqrt(sum(jnp.sum(jnp.square(p.grad._data)) for p in params)) \
        if norm_type == 2.0 else \
        jnp.power(sum(jnp.sum(jnp.power(jnp.abs(p.grad._data), norm_type))
                      for p in params), 1.0 / norm_type)
    clip = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p.grad._data = p.grad._data * clip
    return Tensor(total)


class _Utils:
    clip_grad_norm_ = staticmethod(utils_clip_grad_norm_)

    @staticmethod
    def clip_grad_value_(parameters, clip_value):
        import jax.numpy as jnp
        for p in parameters:
            if p.grad is not None:
                p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)

    @staticmethod
    def parameters_to_vector(parameters):
        from ..ops.manipulation import concat
        return concat([p.flatten() for p in parameters], axis=0)

    @staticmethod
    def vector_to_parameters(vec, parameters):
        import numpy as np
        offset = 0
        for p in parameters:
            n = p.size
            p.set_value(vec[offset:offset + n].reshape(p.shape))
            offset += n

    @staticmethod
    def weight_norm(layer, name="weight", dim=0):
        """nn.utils.weight_norm parity: reparameterize `name` as
        magnitude (name_g) x direction (name_v / ||name_v||), recomputed
        by a forward pre-hook every call so optimizers train g and v."""
        from ..core.tensor import Parameter
        w = getattr(layer, name)
        if dim is None:
            axes = None
        else:
            d = dim % w.ndim
            axes = tuple(a for a in range(w.ndim) if a != d)

        def norm_v(v):
            if axes is not None:
                return (v * v).sum(axis=axes, keepdim=True).sqrt()
            return (v * v).sum().sqrt()

        g = Parameter(norm_v(w)._data)
        v = Parameter(w._data)
        del layer._parameters[name]
        layer.add_parameter(name + "_g", g)
        layer.add_parameter(name + "_v", v)

        def compute(lyr, *unused):
            vv = getattr(lyr, name + "_v")
            gg = getattr(lyr, name + "_g")
            setattr(lyr, name, vv * (gg / norm_v(vv)))

        compute(layer)
        handle = layer.register_forward_pre_hook(
            lambda lyr, inputs: compute(lyr))
        layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
        layer._weight_norm_hooks[name] = (handle, compute)
        return layer

    @staticmethod
    def remove_weight_norm(layer, name="weight"):
        """Fold name_g/name_v back into a plain `name` parameter."""
        from ..core.tensor import Parameter
        handle, compute = layer._weight_norm_hooks.pop(name)
        handle.remove()
        # recompute from the LIVE g/v — the cached attr predates any
        # optimizer steps taken since the last forward
        compute(layer)
        w = getattr(layer, name)
        # drop the cached instance attr: it would shadow the re-added
        # Parameter in __dict__ and freeze forward at today's value
        layer.__dict__.pop(name, None)
        del layer._parameters[name + "_g"]
        del layer._parameters[name + "_v"]
        layer.add_parameter(name, Parameter(w._data))
        return layer

    @staticmethod
    def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                      dim=0):
        """nn.utils.spectral_norm parity: divide `name` by its largest
        singular value each forward (power iteration with a persistent u
        buffer on the layer)."""
        from . import functional as F
        from ..core.tensor import to_tensor
        import numpy as np
        w = getattr(layer, name)
        h = w.shape[dim % w.ndim]
        layer.register_buffer(
            name + "_u",
            to_tensor((np.ones(h, np.float32) / np.sqrt(h))
                      .astype(str(np.dtype(w._data.dtype)))))
        orig = layer._parameters.pop(name)
        layer.add_parameter(name + "_orig", orig)

        def compute(lyr, *unused):
            wn, u_new = F.spectral_norm(
                getattr(lyr, name + "_orig"), axis=dim,
                power_iters=n_power_iterations, epsilon=eps,
                u=getattr(lyr, name + "_u"))
            getattr(lyr, name + "_u").set_value(u_new)
            setattr(lyr, name, wn)

        compute(layer)
        layer.register_forward_pre_hook(lambda lyr, inputs: compute(lyr))
        return layer


utils = _Utils()

from ..parallel.env import DataParallel  # noqa: F401,E402
from . import quant  # noqa: F401,E402  (paddle.nn.quant)
