"""Conv/pool/norm layers — parity with python/paddle/nn/layer/{conv,pooling,
norm}.py (upstream-canonical, unverified — SURVEY.md §0)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from .layer import Layer
from . import functional as F
from . import initializer as I


def _ntuple(v, n):
    return (int(v),) * n if isinstance(v, (int, np.integer)) else tuple(int(x) for x in v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, ndim,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, ndim)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._transpose = transpose
        self._output_padding = output_padding
        self._ndim = ndim
        if transpose:
            wshape = [in_channels, out_channels // groups, *self._kernel_size]
        else:
            wshape = [out_channels, in_channels // groups, *self._kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in,
                                                 negative_slope=np.sqrt(5.0),
                                                 nonlinearity="leaky_relu"))
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups,
                                  output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups,
                                  output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups,
                                  output_size, self._data_format)


# ---- pooling layers --------------------------------------------------------

class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask, self.ceil_mode = return_mask, ceil_mode

    def forward(self, x):
        return F.max_pool1d(x, self.k, self.s, self.p, self.return_mask,
                            self.ceil_mode)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask, self.ceil_mode = return_mask, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.k, self.s, self.p, self.return_mask,
                            self.ceil_mode, self.data_format)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.exclusive, self.ceil_mode = exclusive, ceil_mode

    def forward(self, x):
        return F.avg_pool1d(x, self.k, self.s, self.p, self.exclusive,
                            self.ceil_mode)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.exclusive, self.ceil_mode = exclusive, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.k, self.s, self.p, self.ceil_mode,
                            self.exclusive, None, self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


# ---- norm layers -----------------------------------------------------------

class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape,
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], dtype=self._dtype)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], dtype=self._dtype)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Under SPMD/jit the batch axis is globally sharded and XLA's reduce is
    already cross-replica, so SyncBatchNorm ≡ BatchNorm here (the reference
    needs a dedicated NCCL kernel; the mesh makes it free — SURVEY.md §2.3)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            new.weight.set_value(layer.weight)
            new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    """nn.SpectralNorm parity: normalizes an incoming weight by its
    largest singular value, estimated by power iteration whose left
    singular vector persists across forwards (a non-trainable buffer —
    the reference keeps U/V as persistable vars)."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 dtype="float32", name=None):
        super().__init__()
        self.axis, self.power_iters, self.eps = axis, power_iters, epsilon
        h = weight_shape[axis]
        import numpy as _np
        from ..core.tensor import to_tensor
        # a registered buffer, like the reference's persistable U var —
        # state_dict round-trips the converged singular-vector estimate
        self.register_buffer("weight_u", to_tensor(
            (_np.ones(h, _np.float32) / _np.sqrt(h)).astype(dtype)))

    def forward(self, weight):
        out, u_new = F.spectral_norm(
            weight, axis=self.axis, power_iters=self.power_iters,
            epsilon=self.eps, u=self.weight_u)
        self.weight_u.set_value(u_new)  # persistent power-iteration state
        return out


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask = return_mask
        self.ceil_mode, self.data_format = ceil_mode, data_format

    def forward(self, x):
        return F.max_pool3d(x, self.k, self.s, self.p, self.return_mask,
                            self.ceil_mode, self.data_format)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.exclusive, self.ceil_mode = exclusive, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool3d(x, self.k, self.s, self.p, self.ceil_mode,
                            self.exclusive, None, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxUnPool1D(Layer):
    """Inverse of MaxPool1D(return_mask=True) — reference nn.MaxUnPool1D
    over the phi unpool kernel."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.k, self.s, self.p,
                              self.data_format, self.output_size)


class MaxUnPool2D(MaxUnPool1D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size)

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.k, self.s, self.p,
                              self.data_format, self.output_size)


class MaxUnPool3D(MaxUnPool1D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size)

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.k, self.s, self.p,
                              self.data_format, self.output_size)


class LPPool1D(Layer):
    """paddle.nn.LPPool1D (3.0) — Lp-norm pooling."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self._a = (norm_type, kernel_size, stride, padding, ceil_mode,
                   data_format)

    def forward(self, x):
        from .functional.conv import lp_pool1d
        n, k, s, p, c, df = self._a
        return lp_pool1d(x, n, k, s, p, c, df)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self._a = (norm_type, kernel_size, stride, padding, ceil_mode,
                   data_format)

    def forward(self, x):
        from .functional.conv import lp_pool2d
        n, k, s, p, c, df = self._a
        return lp_pool2d(x, n, k, s, p, c, df)


class FractionalMaxPool2D(Layer):
    """paddle.nn.FractionalMaxPool2D (3.0)."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._a = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        from .functional.conv import fractional_max_pool2d
        o, k, u, m = self._a
        return fractional_max_pool2d(x, o, k, u, m)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._a = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        from .functional.conv import fractional_max_pool3d
        o, k, u, m = self._a
        return fractional_max_pool3d(x, o, k, u, m)
