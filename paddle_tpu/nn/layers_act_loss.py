"""Activation + loss layer classes — parity with
python/paddle/nn/layer/{activation,loss}.py (upstream-canonical, unverified —
SURVEY.md §0)."""
from __future__ import annotations

from .layer import Layer
from . import functional as F
from . import initializer as I


def _act_layer(fname, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            # positional args map onto the functional's keyword order
            self._kwargs = dict(fixed)
            self._args = args
            self._kwargs.update({k: v for k, v in kwargs.items() if k != "name"})

        def forward(self, x):
            return getattr(F, fname)(x, *self._args, **self._kwargs)

    _Act.__name__ = "".join(p.capitalize() for p in fname.split("_"))
    return _Act


ReLU = _act_layer("relu")
ReLU6 = _act_layer("relu6")
GELU = _act_layer("gelu")
SiLU = _act_layer("silu")
Swish = _act_layer("swish")
ELU = _act_layer("elu")
SELU = _act_layer("selu")
CELU = _act_layer("celu")
LeakyReLU = _act_layer("leaky_relu")
Hardshrink = _act_layer("hardshrink")
Softshrink = _act_layer("softshrink")
Tanhshrink = _act_layer("tanhshrink")
Hardtanh = _act_layer("hardtanh")
Hardsigmoid = _act_layer("hardsigmoid")
Hardswish = _act_layer("hardswish")
Mish = _act_layer("mish")
Softplus = _act_layer("softplus")
Softsign = _act_layer("softsign")
LogSigmoid = _act_layer("log_sigmoid")
Tanh = _act_layer("tanh")
Sigmoid = _act_layer("sigmoid")
LogSoftmax = _act_layer("log_softmax")
Maxout = _act_layer("maxout")
ThresholdedReLU = _act_layer("thresholded_relu")


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


# ---- loss layers -----------------------------------------------------------

class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight,
                                                  self.reduction,
                                                  self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon, self.swap = margin, p, epsilon, swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


GLU = _act_layer("glu")
Silu = SiLU  # paddle spells it Silu; keep both


class RReLU(Layer):
    """Randomized leaky ReLU: slope ~ U(lower, upper) in training, the mean
    slope in eval (paddle.nn.RReLU)."""

    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self.delta, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.args)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, *self.args)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean", name=None):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        dist = self.distance_function or (
            lambda a, b: ((a - b) ** 2).sum(-1).sqrt())
        dp = dist(input, positive)
        dn = dist(input, negative)
        if self.swap:
            from .. import ops
            dn = ops.minimum(dn, dist(positive, negative))
        loss = F.relu(dp - dn + self.margin)
        if self.reduction == "mean":
            return loss.mean()
        if self.reduction == "sum":
            return loss.sum()
        return loss


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax (Grave et al.): frequent classes in the head,
    rare classes in down-projected tail clusters (paddle.nn parity)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if not cutoffs or cutoffs != sorted(cutoffs) or \
                cutoffs[-1] >= n_classes:
            raise ValueError("cutoffs must be increasing ints < n_classes")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        from . import layers_common as LC
        self.head = LC.Linear(in_features, self.head_size,
                              bias_attr=head_bias if head_bias else False)
        self.tail = LC.LayerList()
        for i in range(self.n_clusters):
            hsz = int(in_features // (div_value ** (i + 1)))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = LC.Sequential(
                LC.Linear(in_features, max(hsz, 1), bias_attr=False),
                LC.Linear(max(hsz, 1), osz, bias_attr=False))
            self.tail.append(proj)

    def _full_log_prob(self, input):
        from .. import ops
        head_out = self.head(input)
        head_logp = F.log_softmax(head_out, axis=-1)
        pieces = [head_logp[:, :self.cutoffs[0]]]
        for i in range(self.n_clusters):
            cluster_logp = F.log_softmax(self.tail[i](input), axis=-1)
            gate = head_logp[:, self.cutoffs[0] + i:self.cutoffs[0] + i + 1]
            pieces.append(cluster_logp + gate)
        return ops.concat(pieces, axis=-1)

    def forward(self, input, label):
        """Routed target log-prob: head plus only each label's own cluster
        entry is gathered — never materializes the [N, n_classes] matrix.
        (Under static-shape XLA every cluster projection still runs for the
        whole batch, but the tail's div_value down-projection keeps total
        FLOPs ≪ a flat softmax; the dense form stays in log_prob().)"""
        from .. import ops
        label = ops.reshape(label, [-1]).astype("int64")
        head_logp = F.log_softmax(self.head(input), axis=-1)
        cut0 = self.cutoffs[0]
        clipped = ops.clip(label, 0, cut0 - 1)
        output = ops.take_along_axis(
            head_logp, ops.reshape(clipped, [-1, 1]), 1).reshape([-1])
        for i in range(self.n_clusters):
            lo, hi = self.cutoffs[i], self.cutoffs[i + 1]
            in_cluster = (label >= lo).logical_and(label < hi)
            rel = ops.clip(label - lo, 0, hi - lo - 1)
            c_logp = F.log_softmax(self.tail[i](input), axis=-1)
            val = head_logp[:, cut0 + i] + ops.take_along_axis(
                c_logp, ops.reshape(rel, [-1, 1]), 1).reshape([-1])
            output = ops.where(in_cluster, val, output)
        loss = -output.mean()
        return output, loss

    def log_prob(self, input):
        return self._full_log_prob(input)

    def predict(self, input):
        from .. import ops
        return ops.argmax(self._full_log_prob(input), axis=-1)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss (reference nn.HSigmoidLoss): a learned
    binary tree over classes; cost O(log C) per sample instead of a full
    softmax. Default complete-binary-tree paths (custom path tables are
    the deferred tier — see F.hsigmoid_loss)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "custom-tree HSigmoidLoss is deferred (see F.hsigmoid_loss)")
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr)
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_classes - 1,), attr=bias_attr, is_bias=True))

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class GumbelSoftmax(Layer):
    """paddle.nn.GumbelSoftmax — layer form of F.gumbel_softmax."""

    def __init__(self, temperature=1.0, hard=False, axis=-1, name=None):
        super().__init__()
        self._temperature = temperature
        self._hard = hard
        self._axis = axis

    def forward(self, x):
        from . import functional as F
        return F.gumbel_softmax(x, temperature=self._temperature,
                                hard=self._hard, axis=self._axis)


class RNNTLoss(Layer):
    """RNN-Transducer loss (upstream paddle.nn.RNNTLoss — VERDICT r4
    missing 4, the last nn-layer probe miss)."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)
