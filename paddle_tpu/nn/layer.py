"""nn.Layer — the module base class.

Reference parity: python/paddle/nn/layer/layers.py (Layer with
named_parameters/sublayers/state_dict/hooks/train-eval/to) — upstream-canonical
path, unverified (SURVEY.md §0).

TPU-native notes: parameters are eager Tensors (jax.Array-backed). The
functional/jit path gets a pure view of a Layer via
paddle_tpu.jit.functional_call (swap parameter values for traced arrays, call
forward, restore) — that is how one `jax.jit`-compiled train step subsumes the
whole eager stack (SURVEY.md §3.1 "TPU translation").
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import numpy as np
import jax

from ..core.tensor import Tensor, Parameter
from ..core import dtype as dtypes
from . import initializer as I


class ParamAttr:
    """paddle.ParamAttr parity: bundles name/initializer/lr/trainable."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        return ParamAttr()


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        # use object.__setattr__: our __setattr__ consults these dicts
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._casted_by_pure_fp16 = False
        self._hook_id = 0

    # ---- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            subs.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            subs[name] = value
            params.pop(name, None)
            self.__dict__.pop(name, None)
        elif bufs is not None and name in bufs:
            bufs[name] = value
        elif params is not None and name in params:
            if value is None:
                del params[name]
                self.__dict__[name] = None
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # ---- construction helpers ---------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype or self._dtype)
        # precedence: explicit ParamAttr initializer > global initializer
        # (set_global_initializer) > the layer's built-in default
        init = attr.initializer
        if init is None:
            init = I._global_default(is_bias) or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters.pop(name, None)
            self.__dict__[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- iteration ---------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True,
                                           layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return [l for l in self._sub_layers.values() if l is not None]

    def named_children(self):
        return [(n, l) for n, l in self._sub_layers.items() if l is not None]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # ---- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        out = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            out[name] = p
        for name, layer in self.named_sublayers(
                prefix=structured_name_prefix.rstrip("."), include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                out[(f"{name}.{bname}" if name else bname)] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            v_arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
            if tuple(v_arr.shape) != tuple(tgt._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: loaded {v_arr.shape} vs "
                    f"{tuple(tgt._data.shape)}")
            tgt.set_value(v_arr)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ---- mode / dtype / device ---------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def apply(self, fn: Callable):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtypes.convert_dtype(dtype)
            for _, p in self.named_parameters():
                if dtypes.is_floating_point(p.dtype):
                    p._data = p._data.astype(d)
            for _, b in self.named_buffers():
                if dtypes.is_floating_point(b.dtype):
                    b._data = b._data.astype(d)
            for layer in self.sublayers(include_self=True):
                layer._dtype = d
        if device is not None:
            from ..core.device import set_device, Place
            place = device if isinstance(device, Place) else set_device(device)
            for t in list(self.parameters()) + list(self.buffers()):
                t._data = jax.device_put(t._data, place.jax_device)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ---- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        hid = self._hook_id
        self._hook_id += 1
        self._forward_pre_hooks[hid] = hook
        return _HookRemover(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = self._hook_id
        self._hook_id += 1
        self._forward_post_hooks[hid] = hook
        return _HookRemover(self._forward_post_hooks, hid)

    # ---- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f"{self.__class__.__name__} must implement forward()")

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # ---- repr ---------------------------------------------------------------
    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class _HookRemover:
    def __init__(self, store, hid):
        self._store, self._hid = store, hid

    def remove(self):
        self._store.pop(self._hid, None)
