"""Recurrent layers — parity with python/paddle/nn/layer/rnn.py
(upstream-canonical, unverified — SURVEY.md §0).

TPU-native: the time loop is jax.lax.scan (compiled once, no per-step python)
— the reference's cudnn RNN kernels become one fused XLA while-loop whose body
is MXU matmuls."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .layer import Layer
from . import initializer as I
from ..ops._registry import eager


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, n_gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        g = n_gates
        self.weight_ih = self.create_parameter(
            [g * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [g * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [g * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [g * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size, 1, **kw)
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            from ..ops.creation import zeros
            states = zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)

        def raw(x, h, wi, wh, bi, bh):
            z = x @ wi.T + bi + h @ wh.T + bh
            return jnp.tanh(z) if self.activation == "tanh" else jax.nn.relu(z)

        out = eager(raw, (inputs, states, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh), {}, name="rnn_cell")
        return out, out


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 4, **kw)

    def forward(self, inputs, states=None):
        if states is None:
            from ..ops.creation import zeros
            z = zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)
            states = (z, z.clone())
        h, c = states

        def raw(x, hh, cc, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hh @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * cc + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = eager(raw, (inputs, h, c, self.weight_ih, self.weight_hh,
                                   self.bias_ih, self.bias_hh), {}, name="lstm_cell")
        return h_new, (h_new, c_new)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 3, **kw)

    def forward(self, inputs, states=None):
        if states is None:
            from ..ops.creation import zeros
            states = zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)

        def raw(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h

        out = eager(raw, (inputs, states, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh), {}, name="gru_cell")
        return out, out


class _RNNBase(Layer):
    """Multi-layer (bi)directional RNN driven by lax.scan over time."""

    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        self.num_directions = num_dir
        n_gates = {"RNN": 1, "LSTM": 4, "GRU": 3}[self.MODE]
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(num_dir):
                in_size = input_size if layer == 0 else hidden_size * num_dir
                sfx = f"_{layer}" + ("_reverse" if d else "")
                self.add_parameter("weight_ih" + sfx, self.create_parameter(
                    [n_gates * hidden_size, in_size], default_initializer=u))
                self.add_parameter("weight_hh" + sfx, self.create_parameter(
                    [n_gates * hidden_size, hidden_size], default_initializer=u))
                self.add_parameter("bias_ih" + sfx, self.create_parameter(
                    [n_gates * hidden_size], is_bias=True, default_initializer=u))
                self.add_parameter("bias_hh" + sfx, self.create_parameter(
                    [n_gates * hidden_size], is_bias=True, default_initializer=u))

    def _cell(self, x, h, c, wi, wh, bi, bh):
        if self.MODE == "LSTM":
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            return o * jnp.tanh(c_new), c_new
        if self.MODE == "GRU":
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h, c
        z = x @ wi.T + bi + h @ wh.T + bh
        h_new = jnp.tanh(z) if self.activation == "tanh" else jax.nn.relu(z)
        return h_new, c

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.MODE == "LSTM"
        num_dir = self.num_directions

        params = []
        for layer in range(self.num_layers):
            for d in range(num_dir):
                sfx = f"_{layer}" + ("_reverse" if d else "")
                params += [getattr(self, "weight_ih" + sfx),
                           getattr(self, "weight_hh" + sfx),
                           getattr(self, "bias_ih" + sfx),
                           getattr(self, "bias_hh" + sfx)]

        init_h = init_c = None
        extra = []
        if initial_states is not None:
            if is_lstm:
                init_h, init_c = initial_states
                extra = [init_h, init_c]
            else:
                init_h = initial_states
                extra = [init_h]

        time_major = self.time_major
        nl, hs, mode = self.num_layers, self.hidden_size, self.MODE

        def raw(*arrs):
            x = arrs[0]
            ps = arrs[1:1 + len(params)]
            rest = arrs[1 + len(params):]
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, F]
            t_steps, b = x.shape[0], x.shape[1]
            if rest:
                h0 = rest[0]
                c0 = rest[1] if is_lstm else None
            else:
                h0 = jnp.zeros((nl * num_dir, b, hs), dtype=x.dtype)
                c0 = jnp.zeros((nl * num_dir, b, hs), dtype=x.dtype) if is_lstm else None
            hs_out, cs_out = [], []
            out = x
            pi = 0
            for layer in range(nl):
                dir_outs = []
                for d in range(num_dir):
                    wi, wh, bi, bh = ps[pi:pi + 4]
                    pi += 4
                    idx = layer * num_dir + d
                    seq = out if d == 0 else jnp.flip(out, axis=0)

                    def step(carry, xt):
                        h, c = carry
                        h_new, c_new = self._cell(xt, h, c, wi, wh, bi, bh)
                        return (h_new, c_new), h_new

                    czero = c0[idx] if is_lstm else jnp.zeros_like(h0[idx])
                    (h_fin, c_fin), ys = jax.lax.scan(step, (h0[idx], czero), seq)
                    if d == 1:
                        ys = jnp.flip(ys, axis=0)
                    dir_outs.append(ys)
                    hs_out.append(h_fin)
                    if is_lstm:
                        cs_out.append(c_fin)
                out = jnp.concatenate(dir_outs, axis=-1) if num_dir == 2 else dir_outs[0]
            final_h = jnp.stack(hs_out, axis=0)
            outputs = out if time_major else jnp.swapaxes(out, 0, 1)
            if is_lstm:
                return outputs, final_h, jnp.stack(cs_out, axis=0)
            return outputs, final_h

        res = eager(raw, tuple([inputs] + params + extra), {}, name=self.MODE.lower())
        if is_lstm:
            outputs, h, c = res
            return outputs, (h, c)
        outputs, h = res
        return outputs, h


class SimpleRNN(_RNNBase):
    MODE = "RNN"


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class RNN(Layer):
    """Wrapper running an arbitrary cell over time (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        axis = 0 if self.time_major else 1
        steps = inputs.shape[axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        outs = []
        states = initial_states
        from ..ops.manipulation import stack
        for t in order:
            xt = inputs[:, t] if axis == 1 else inputs[t]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, False, time_major)
        self.bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        sf = sb = None
        if initial_states is not None:
            sf, sb = initial_states
        of, stf = self.fw(inputs, sf)
        ob, stb = self.bw(inputs, sb)
        from ..ops.manipulation import concat
        return concat([of, ob], axis=-1), (stf, stb)


RNNCellBase = _RNNCellBase  # public name (paddle.nn.RNNCellBase)
