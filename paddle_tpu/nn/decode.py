"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference analog: paddle.nn.BeamSearchDecoder / paddle.nn.dynamic_decode
(python/paddle/nn/decode.py — the Decoder protocol with
initialize/step/finalize driven by a host loop; upstream-canonical,
unverified, SURVEY.md §0 / §2.4 paddle.nn row).

TPU-native note: this is the EAGER decoding facade for API parity —
the compiled, KV-cache path for the flagship LLMs is
paddle_tpu.nlp.generation (lax.scan decode loop, no host round-trips).
Beam state here is batch-major [B, beam] and the loop is host-side like
the reference's, which is fine at the RNN/seq2seq scale this API serves.
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class BeamSearchDecoder:
    """Beam search over an RNN cell (nn.BeamSearchDecoder parity).

    cell: an RNNCell-like layer — cell(inputs [N, ...], states) ->
    (outputs [N, H], new_states). embedding_fn maps token ids -> inputs;
    output_fn maps cell outputs -> vocab logits.
    """

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- Decoder protocol ---------------------------------------------------
    def initialize(self, initial_cell_states):
        """states: pytree of [B, ...] tensors → tiled to [B*beam, ...]."""
        def tile(s):
            a = _np(s)
            return to_tensor(np.repeat(a, self.beam_size, axis=0))

        states = self._map(initial_cell_states, tile)
        b = _np(self._first(initial_cell_states)).shape[0]
        self._batch = b
        tokens = np.full((b * self.beam_size,), self.start_token, np.int64)
        # beam 0 live, others -inf so step 1 expands only beam 0
        log_probs = np.full((b, self.beam_size), -1e9, np.float32)
        log_probs[:, 0] = 0.0
        finished = np.zeros((b, self.beam_size), bool)
        inputs = self._embed(tokens)
        return inputs, (states, to_tensor(log_probs), finished), \
            to_tensor(finished)

    def step(self, time, inputs, states, **kwargs):
        cell_states, log_probs, finished = states
        out, new_states = self.cell(inputs, cell_states)
        logits = self.output_fn(out) if self.output_fn is not None else out
        from . import functional as F
        logp = _np(F.log_softmax(logits, axis=-1))             # [B*k, V]
        B, k = self._batch, self.beam_size
        V = logp.shape[-1]
        logp = logp.reshape(B, k, V)
        # finished beams only extend with end_token at no cost
        fin = finished.reshape(B, k)
        mask = np.full((B, k, V), -1e9, np.float32)
        mask[:, :, self.end_token] = 0.0
        logp = np.where(fin[:, :, None], mask, logp)
        total = _np(log_probs)[:, :, None] + logp               # [B, k, V]
        flat = total.reshape(B, k * V)
        top_idx = np.argsort(-flat, axis=1)[:, :k]              # [B, k]
        top_score = np.take_along_axis(flat, top_idx, axis=1)
        parent = top_idx // V                                   # [B, k]
        token = top_idx % V                                     # [B, k]
        new_fin = np.take_along_axis(fin, parent, axis=1) | \
            (token == self.end_token)

        def gather(s):
            a = _np(s).reshape((B, k) + _np(s).shape[1:])
            g = np.take_along_axis(
                a, parent.reshape((B, k) + (1,) * (a.ndim - 2)), axis=1)
            return to_tensor(g.reshape((B * k,) + a.shape[2:]))

        gathered = self._map(new_states, gather)
        next_inputs = self._embed(token.reshape(-1).astype(np.int64))
        outputs = {"predicted_ids": to_tensor(token),
                   "parent_ids": to_tensor(parent),
                   "scores": to_tensor(top_score)}
        next_states = (gathered, to_tensor(top_score), new_fin)
        return outputs, next_states, next_inputs, to_tensor(new_fin)

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrack parent pointers → [B, T, beam] token paths."""
        pred = _np(outputs["predicted_ids"])                    # [T, B, k]
        par = _np(outputs["parent_ids"])
        T, B, k = pred.shape
        beams = np.zeros((B, T, k), np.int64)
        idx = np.tile(np.arange(k), (B, 1))                     # [B, k]
        for t in range(T - 1, -1, -1):
            beams[:, t] = np.take_along_axis(pred[t], idx, axis=1)
            idx = np.take_along_axis(par[t], idx, axis=1)
        return to_tensor(beams)

    # -- helpers ------------------------------------------------------------
    def _embed(self, tokens):
        t = to_tensor(np.asarray(tokens, np.int64))
        return self.embedding_fn(t) if self.embedding_fn is not None else t

    @staticmethod
    def _map(tree, fn):
        if isinstance(tree, (list, tuple)):
            return type(tree)(BeamSearchDecoder._map(s, fn) for s in tree)
        if isinstance(tree, dict):
            return {n: BeamSearchDecoder._map(s, fn) for n, s in tree.items()}
        return fn(tree)

    @staticmethod
    def _first(tree):
        if isinstance(tree, (list, tuple)):
            return BeamSearchDecoder._first(tree[0])
        if isinstance(tree, dict):
            return BeamSearchDecoder._first(next(iter(tree.values())))
        return tree


def dynamic_decode(decoder, inits=None, max_step_num: int = 100,
                   output_time_major: bool = False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive a Decoder's initialize/step until every sequence finishes or
    max_step_num is hit (nn.dynamic_decode parity). Returns
    (outputs, final_states) with outputs stacked over time (plus lengths
    when return_length)."""
    inputs, states, finished = decoder.initialize(inits)
    collected: dict = {}
    lengths = prev_fin = None
    for t in range(max_step_num):
        outputs, states, inputs, finished = decoder.step(
            t, inputs, states, **kwargs)
        for name, v in outputs.items():
            collected.setdefault(name, []).append(_np(v))
        fin = _np(finished)
        if lengths is None:
            lengths = np.zeros(fin.shape, np.int64)
            prev_fin = np.zeros(fin.shape, bool)
        # the step that EMITS a sequence's eos still counts toward its
        # length: freeze only beams that were already finished before it
        lengths = np.where(prev_fin, lengths, t + 1)
        prev_fin = fin
        if bool(np.all(fin)):
            break
    stacked = {n: np.stack(v, axis=0) for n, v in collected.items()}
    if hasattr(decoder, "finalize"):
        final = decoder.finalize(
            {n: to_tensor(v) for n, v in stacked.items()}, states,
            to_tensor(lengths))
    else:
        axis = 0 if output_time_major else 1
        final = {n: to_tensor(np.moveaxis(v, 0, axis))
                 for n, v in stacked.items()}
    if return_length:
        return final, states, to_tensor(lengths)
    return final, states
