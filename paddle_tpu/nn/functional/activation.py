"""Activation functionals — python/paddle/nn/functional/activation.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._registry import defop, as_array, eager

relu = defop("relu", lambda x, name=None: jax.nn.relu(x))
relu6 = defop("relu6", lambda x, name=None: jnp.clip(x, 0, 6))
relu_ = None  # in-place attached by nn/functional/__init__


def _gelu_raw(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=approximate)


gelu = defop("gelu", _gelu_raw)
silu = defop("silu", lambda x, name=None: jax.nn.silu(x))
swish = defop("swish", lambda x, name=None: jax.nn.silu(x))
elu = defop("elu", lambda x, alpha=1.0, name=None: jax.nn.elu(x, alpha=alpha))
selu = defop("selu", lambda x,
             scale=1.0507009873554804934193349852946,
             alpha=1.6732632423543772848170429916717, name=None:
             scale * jnp.where(x > 0, x, alpha * jnp.expm1(x)))
celu = defop("celu", lambda x, alpha=1.0, name=None: jax.nn.celu(x, alpha=alpha))
leaky_relu = defop("leaky_relu", lambda x, negative_slope=0.01, name=None:
                   jax.nn.leaky_relu(x, negative_slope=negative_slope))
prelu = defop("prelu", lambda x, weight, data_format="NCHW", name=None:
              _prelu_raw(x, as_array(weight), data_format))


def _prelu_raw(x, w, data_format):
    if w.size == 1:
        slope = w.reshape(())
    else:
        shape = [1] * x.ndim
        axis = 1 if data_format[1] == "C" else x.ndim - 1
        shape[axis] = w.size
        slope = w.reshape(shape)
    return jnp.where(x >= 0, x, slope * x)


def _rrelu_raw(x, lower, upper, training, key):
    if training:
        slope = jax.random.uniform(key, x.shape, jnp.float32, lower, upper) \
            .astype(x.dtype)
    else:
        slope = (lower + upper) / 2
    return jnp.where(x >= 0, x, x * slope)


def rrelu(x, lower=1. / 8., upper=1. / 3., training=True, name=None):
    from ...core import random as prandom
    key = prandom.next_key()
    return eager(lambda a: _rrelu_raw(a, lower, upper, training, key),
                 (x,), {}, name="rrelu")
hardshrink = defop("hardshrink", lambda x, threshold=0.5, name=None:
                   jnp.where(jnp.abs(x) > threshold, x, 0.0))
softshrink = defop("softshrink", lambda x, threshold=0.5, name=None:
                   jnp.where(x > threshold, x - threshold,
                             jnp.where(x < -threshold, x + threshold, 0.0)))
tanhshrink = defop("tanhshrink", lambda x, name=None: x - jnp.tanh(x))
hardtanh = defop("hardtanh", lambda x, min=-1.0, max=1.0, name=None:
                 jnp.clip(x, min, max))
hardsigmoid = defop("hardsigmoid", lambda x, slope=0.1666667, offset=0.5, name=None:
                    jnp.clip(x * slope + offset, 0.0, 1.0))
hardswish = defop("hardswish", lambda x, name=None:
                  x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0)
mish = defop("mish", lambda x, name=None: x * jnp.tanh(jax.nn.softplus(x)))
softplus = defop("softplus", lambda x, beta=1.0, threshold=20.0, name=None:
                 jnp.where(x * beta > threshold, x,
                           (1.0 / beta) * jnp.log1p(jnp.exp(beta * x))))
softsign = defop("softsign", lambda x, name=None: jax.nn.soft_sign(x))
log_sigmoid = defop("log_sigmoid", lambda x, name=None: jax.nn.log_sigmoid(x))
tanh = defop("f_tanh", lambda x, name=None: jnp.tanh(x))
sigmoid = defop("f_sigmoid", lambda x, name=None: jax.nn.sigmoid(x))


def _softmax_raw(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtypes
    if dtype is not None:
        x = x.astype(dtypes.convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


softmax = defop("softmax", _softmax_raw)
log_softmax = defop("log_softmax", lambda x, axis=-1, dtype=None, name=None:
                    jax.nn.log_softmax(x, axis=axis))
gumbel_softmax = defop("gumbel_softmax", lambda x, temperature=1.0, hard=False, axis=-1, name=None:
                       _gumbel_softmax_raw(x, temperature, hard, axis))


def _gumbel_softmax_raw(x, temperature, hard, axis):
    from ...core import random as prandom
    g = jax.random.gumbel(prandom.next_key(), x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        one_hot = jax.nn.one_hot(jnp.argmax(y, axis=axis), y.shape[axis],
                                 axis=axis, dtype=y.dtype)
        y = jax.lax.stop_gradient(one_hot - y) + y  # straight-through
    return y


def _glu_raw(x, axis=-1, name=None):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


glu = defop("glu", _glu_raw)


def _maxout_raw(x, groups, axis=1, name=None):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


maxout = defop("maxout", _maxout_raw)
thresholded_relu = defop("thresholded_relu", lambda x, threshold=1.0, name=None:
                         jnp.where(x > threshold, x, 0.0))
