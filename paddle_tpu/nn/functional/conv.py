"""Convolution & pooling functionals — python/paddle/nn/functional/conv.py,
pooling.py parity (upstream-canonical, unverified — SURVEY.md §0).

TPU-native: convs lower to XLA conv_general_dilated, which the TPU compiler
tiles onto the MXU directly — this is the entire 'gpudnn' layer of the
reference (paddle/phi/kernels/gpudnn/conv_kernel.cu) collapsed into one call.
Layout note: paddle default is NCHW; XLA:TPU internally prefers NHWC and
transposes automatically, so we keep API-level NCHW and let the compiler
choose (same decision the reference makes per-backend with its layout
transformer)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._registry import defop, as_array, eager


def _ntuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _conv_padding(padding, spatial, strides=None, dilations=None, ksize=None):
    """Paddle padding spec → lax padding list. Supports int, list, pairs,
    'SAME', 'VALID'."""
    n = spatial
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # full-rank form [[0,0],[0,0],[h0,h1],[w0,w1]]
        return [tuple(int(x) for x in p) for p in padding[-n:]]
    raise ValueError(f"bad padding {padding}")


def _conv_raw(x, weight, bias, stride, padding, dilation, groups, ndim,
              data_format, transpose=False, output_padding=0):
    chan_last = data_format.endswith("C")
    letters = "DHW"[3 - ndim:]
    if chan_last:
        dn_in = "N" + letters + "C"
    else:
        dn_in = "NC" + letters
    dn = (dn_in, "OI" + letters, dn_in)
    strides = _ntuple(stride, ndim)
    dilations = _ntuple(dilation, ndim)
    pad = _conv_padding(padding, ndim)
    if not transpose:
        out = jax.lax.conv_general_dilated(
            x, weight, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups)
    else:
        # conv_transpose: paddle weight layout [in_c, out_c/groups, *k]
        opad = _ntuple(output_padding, ndim)
        if isinstance(pad, str):
            lax_pad = pad
        else:
            # paddle conv_transpose pad p → lax transpose padding: for each dim
            # (k-1)*d - p on both sides, + output_padding on the high side
            k = weight.shape[2:]
            lax_pad = [
                (dilations[i] * (k[i] - 1) - pad[i][0],
                 dilations[i] * (k[i] - 1) - pad[i][1] + opad[i])
                for i in range(ndim)
            ]
        # grouped transpose: split, run per group, concat (XLA fuses)
        w = jnp.swapaxes(weight, 0, 1)  # [out_c/groups, in_c, *k]
        w = jnp.flip(w, axis=tuple(range(2, 2 + ndim)))
        if groups == 1:
            out = jax.lax.conv_general_dilated(
                x, w, window_strides=(1,) * ndim, padding=lax_pad,
                lhs_dilation=strides, dimension_numbers=dn)
        else:
            ci_ax = dn_in.index("C")
            xs = jnp.split(x, groups, axis=ci_ax)
            ws = jnp.split(w, groups, axis=1)
            outs = [jax.lax.conv_general_dilated(
                xg, wg, window_strides=(1,) * ndim, padding=lax_pad,
                lhs_dilation=strides, dimension_numbers=dn)
                for xg, wg in zip(xs, ws)]
            out = jnp.concatenate(outs, axis=ci_ax)
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[dn_in.index("C")] = bias.shape[0]
        out = out + bias.reshape(bshape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    args = (x, weight) if bias is None else (x, weight, bias)
    return eager(lambda *a: _conv_raw(a[0], a[1], a[2] if len(a) > 2 else None,
                                      stride, padding, dilation, groups, 1,
                                      data_format), args, {}, name="conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    args = (x, weight) if bias is None else (x, weight, bias)
    return eager(lambda *a: _conv_raw(a[0], a[1], a[2] if len(a) > 2 else None,
                                      stride, padding, dilation, groups, 2,
                                      data_format), args, {}, name="conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    args = (x, weight) if bias is None else (x, weight, bias)
    return eager(lambda *a: _conv_raw(a[0], a[1], a[2] if len(a) > 2 else None,
                                      stride, padding, dilation, groups, 3,
                                      data_format), args, {}, name="conv3d")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCL", name=None):
    args = (x, weight) if bias is None else (x, weight, bias)
    return eager(lambda *a: _conv_raw(a[0], a[1], a[2] if len(a) > 2 else None,
                                      stride, padding, dilation, groups, 1,
                                      data_format, transpose=True,
                                      output_padding=output_padding),
                 args, {}, name="conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW", name=None):
    args = (x, weight) if bias is None else (x, weight, bias)
    return eager(lambda *a: _conv_raw(a[0], a[1], a[2] if len(a) > 2 else None,
                                      stride, padding, dilation, groups, 2,
                                      data_format, transpose=True,
                                      output_padding=output_padding),
                 args, {}, name="conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCDHW", name=None):
    args = (x, weight) if bias is None else (x, weight, bias)
    return eager(lambda *a: _conv_raw(a[0], a[1], a[2] if len(a) > 2 else None,
                                      stride, padding, dilation, groups, 3,
                                      data_format, transpose=True,
                                      output_padding=output_padding),
                 args, {}, name="conv3d_transpose")


# ---- pooling ---------------------------------------------------------------

def _pool_raw(x, ksize, strides, padding, ndim, op, data_format="NCHW",
              ceil_mode=False, exclusive=True, count_include_pad=False):
    chan_last = data_format.endswith("C")
    k = _ntuple(ksize, ndim)
    s = _ntuple(strides if strides is not None else ksize, ndim)
    pad = _conv_padding(padding, ndim)
    if chan_last:
        window = (1,) + k + (1,)
        stride_full = (1,) + s + (1,)
        pad_full = [(0, 0)] + (pad if isinstance(pad, list) else pad) + [(0, 0)] \
            if not isinstance(pad, str) else pad
    else:
        window = (1, 1) + k
        stride_full = (1, 1) + s
        pad_full = [(0, 0), (0, 0)] + pad if not isinstance(pad, str) else pad
    if op == "max":
        init = -jnp.inf if np.dtype(x.dtype).kind == "f" else np.iinfo(np.dtype(x.dtype)).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, stride_full,
                                     pad_full)
    # avg
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride_full, pad_full)
    if count_include_pad or (isinstance(pad_full, str)) or all(
            p == (0, 0) for p in (pad_full if isinstance(pad_full, list) else [])):
        denom = np.prod(k)
        return summed / denom
    ones = jnp.ones_like(x)
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride_full, pad_full)
    return summed / counts


def _max_pool_indices(x, ksize, stride, padding, nd):
    """Flat-spatial argmax index per window (paddle return_mask parity),
    NCHW-family layouts, any spatial rank."""
    spatial = x.shape[2:]
    size = int(np.prod(spatial))
    flat_idx = jnp.arange(size, dtype=jnp.float64).reshape((1, 1) + spatial)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    k = _ntuple(ksize, nd)
    s = _ntuple(stride if stride is not None else ksize, nd)
    pad = _conv_padding(padding, nd)
    pad_full = [(0, 0), (0, 0)] + pad if not isinstance(pad, str) else pad

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    neg = jnp.asarray(-jnp.inf if np.dtype(x.dtype).kind == "f"
                      else np.iinfo(np.dtype(x.dtype)).min, x.dtype)
    _, idxs = jax.lax.reduce_window(
        (x, flat_idx), (neg, jnp.asarray(0.0, flat_idx.dtype)), sel,
        (1, 1) + k, (1, 1) + s, pad_full)
    return idxs.astype(jnp.int64)


def _max_pool_nd(x, kernel_size, stride, padding, return_mask, ceil_mode,
                 data_format, nd, name):
    out = eager(lambda a: _pool_raw(a, kernel_size, stride, padding, nd,
                                    "max", data_format, ceil_mode),
                (x,), {}, name=name)
    if return_mask:
        if data_format.endswith("C"):
            raise NotImplementedError(
                f"{name}: return_mask with channels-last layout "
                "(paddle_tpu/nn/functional/conv.py)")
        idx = eager(lambda a: _max_pool_indices(a, kernel_size, stride,
                                                padding, nd),
                    (x,), {}, name=name + "_mask")
        return out, idx
    return out


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _max_pool_nd(x, kernel_size, stride, padding, return_mask,
                        ceil_mode, data_format, 1, "max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool_nd(x, kernel_size, stride, padding, return_mask,
                        ceil_mode, data_format, 2, "max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool_nd(x, kernel_size, stride, padding, return_mask,
                        ceil_mode, data_format, 3, "max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return eager(lambda a: _pool_raw(a, kernel_size, stride, padding, 1, "avg",
                                     data_format, ceil_mode,
                                     count_include_pad=not exclusive),
                 (x,), {}, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return eager(lambda a: _pool_raw(a, kernel_size, stride, padding, 2, "avg",
                                     data_format, ceil_mode,
                                     count_include_pad=not exclusive),
                 (x,), {}, name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return eager(lambda a: _pool_raw(a, kernel_size, stride, padding, 3, "avg",
                                     data_format, ceil_mode,
                                     count_include_pad=not exclusive),
                 (x,), {}, name="avg_pool3d")


def _adaptive_pool_raw(x, output_size, ndim, op):
    spatial = x.shape[2:]
    out_size = _ntuple(output_size, ndim)
    out_size = tuple(spatial[i] if out_size[i] is None else out_size[i]
                     for i in range(ndim))
    if all(spatial[i] % out_size[i] == 0 for i in range(ndim)):
        # divisible fast path: reshape + reduce
        shape = list(x.shape[:2])
        red_axes = []
        for i in range(ndim):
            shape += [out_size[i], spatial[i] // out_size[i]]
            red_axes.append(2 + 2 * i + 1)
        xr = x.reshape(shape)
        return jnp.max(xr, axis=tuple(red_axes)) if op == "max" else \
            jnp.mean(xr, axis=tuple(red_axes))
    # general: per-output-bin slices (static; unrolled at trace time)
    def pool_axis(a, axis, n_out):
        n_in = a.shape[axis]
        pieces = []
        for i in range(n_out):
            lo = (i * n_in) // n_out
            hi = -(-((i + 1) * n_in) // n_out)
            seg = jax.lax.slice_in_dim(a, lo, hi, axis=axis)
            red = jnp.max(seg, axis=axis, keepdims=True) if op == "max" else \
                jnp.mean(seg, axis=axis, keepdims=True)
            pieces.append(red)
        return jnp.concatenate(pieces, axis=axis)

    out = x
    for i in range(ndim):
        out = pool_axis(out, 2 + i, out_size[i])
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    return eager(lambda a: _adaptive_pool_raw(a, output_size, 1, "avg"), (x,), {},
                 name="adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return eager(lambda a: _adaptive_pool_raw(a, output_size, 2, "avg"), (x,), {},
                 name="adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return eager(lambda a: _adaptive_pool_raw(a, output_size, 3, "avg"), (x,), {},
                 name="adaptive_avg_pool3d")


def _adaptive_max_indices(x, output_size, ndim):
    """Flat-spatial argmax per adaptive bin — divisible sizes only (the
    common unpooling case; general bins would need per-bin unrolled argmax)."""
    spatial = x.shape[2:]
    out_size = _ntuple(output_size, ndim)
    out_size = tuple(spatial[i] if out_size[i] is None else out_size[i]
                     for i in range(ndim))
    if not all(spatial[i] % out_size[i] == 0 for i in range(ndim)):
        raise NotImplementedError(
            "adaptive_max_pool return_mask needs input sizes divisible by "
            "output sizes (paddle_tpu/nn/functional/conv.py)")
    size = int(np.prod(spatial))
    flat_idx = jnp.arange(size, dtype=jnp.int64).reshape((1, 1) + spatial)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    shape = list(x.shape[:2])
    for i in range(ndim):
        shape += [out_size[i], spatial[i] // out_size[i]]
    # bring window axes last, flatten, joint argmax
    perm = [0, 1] + [2 + 2 * i for i in range(ndim)] + \
        [3 + 2 * i for i in range(ndim)]
    xr = jnp.transpose(x.reshape(shape), perm)
    ir = jnp.transpose(flat_idx.reshape(shape), perm)
    win = int(np.prod(xr.shape[2 + ndim:]))
    xr = xr.reshape(xr.shape[:2 + ndim] + (win,))
    ir = ir.reshape(ir.shape[:2 + ndim] + (win,))
    am = jnp.argmax(xr, axis=-1)
    return jnp.take_along_axis(ir, am[..., None], axis=-1)[..., 0]


def _adaptive_max_pool(x, output_size, return_mask, ndim, name):
    out = eager(lambda a: _adaptive_pool_raw(a, output_size, ndim, "max"),
                (x,), {}, name=name)
    if return_mask:
        idx = eager(lambda a: _adaptive_max_indices(a, output_size, ndim),
                    (x,), {}, name=name + "_mask")
        return out, idx
    return out


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, output_size, return_mask, 1,
                              "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, output_size, return_mask, 2,
                              "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, output_size, return_mask, 3,
                              "adaptive_max_pool3d")


# ---------------------------------------------------------------------------
# Max un-pooling (reference: phi unpool kernels behind F.max_unpool{1,2,3}d)
# ---------------------------------------------------------------------------

def _max_unpool_raw(x, indices, nd, kernel_size, stride, padding,
                    output_size, data_format="NCL"):
    if not data_format.startswith("NC"):
        raise NotImplementedError(
            "max_unpool with channels-last layout is not supported "
            "(mirrors max_pool's return_mask restriction)")
    ksize = _ntuple(kernel_size, nd)
    strides = _ntuple(stride if stride is not None else kernel_size, nd)
    pads = _ntuple(padding, nd)
    sp_in = x.shape[2:]
    if output_size is None:
        output_size = tuple(
            (s - 1) * st - 2 * p + k
            for s, st, p, k in zip(sp_in, strides, pads, ksize))
    else:
        output_size = tuple(output_size)[-nd:]
    N, C = x.shape[:2]
    flat = 1
    for s in output_size:
        flat *= s
    xi = x.reshape(N, C, -1)
    ii = indices.reshape(N, C, -1).astype(jnp.int32)
    out = jnp.zeros((N, C, flat), x.dtype)
    out = out.at[jnp.arange(N)[:, None, None],
                 jnp.arange(C)[None, :, None], ii].set(xi)
    return out.reshape((N, C) + output_size)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Inverse of max_pool1d(return_mask=True): scatter each pooled value
    back to the argmax position its mask recorded; everything else zero."""
    return eager(lambda a, i: _max_unpool_raw(a, i, 1, kernel_size, stride,
                                              padding, output_size,
                                              data_format),
                 (x, indices), {}, name="max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return eager(lambda a, i: _max_unpool_raw(a, i, 2, kernel_size, stride,
                                              padding, output_size,
                                              data_format),
                 (x, indices), {}, name="max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return eager(lambda a, i: _max_unpool_raw(a, i, 3, kernel_size, stride,
                                              padding, output_size,
                                              data_format),
                 (x, indices), {}, name="max_unpool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """paddle.nn.functional.lp_pool1d (3.0): Lp-norm pooling —
    (sum |x|^p)^(1/p) over each window (avg-pool of x^p, rescaled)."""
    p = float(norm_type)

    def raw(a):
        powed = jnp.abs(a.astype(jnp.float32)) ** p
        pooled = _pool_raw(powed, kernel_size, stride, padding, 1, "avg",
                           data_format, ceil_mode, count_include_pad=True)
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        return ((pooled * k) ** (1.0 / p)).astype(a.dtype)

    return eager(raw, (x,), {}, name="lp_pool1d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)

    def raw(a):
        powed = jnp.abs(a.astype(jnp.float32)) ** p
        pooled = _pool_raw(powed, kernel_size, stride, padding, 2, "avg",
                           data_format, ceil_mode, count_include_pad=True)
        ks = _ntuple(kernel_size, 2)
        return ((pooled * (ks[0] * ks[1])) ** (1.0 / p)).astype(a.dtype)

    return eager(raw, (x,), {}, name="lp_pool2d")


def _fractional_pool(a, output_size, ndim, random_u):
    """Fractional max pooling (Graham): pseudo-random window boundaries
    from the u in (0,1) — deterministic per call via the framework RNG
    unless random_u is given."""
    spatial = a.shape[2:]
    outs = _ntuple(output_size, ndim)
    slices = []
    for d in range(ndim):
        n_in, n_out = spatial[d], outs[d]
        alpha = n_in / n_out
        u = random_u if random_u is not None else 0.5
        idx = jnp.floor(alpha * (jnp.arange(n_out) + u)).astype(int)
        starts = jnp.concatenate([jnp.zeros((1,), idx.dtype), idx[:-1]])
        ends = idx.at[-1].set(n_in)
        slices.append((starts, ends))

    def pool_axis(arr, axis, starts, ends):
        n_out = starts.shape[0]
        segs = []
        for i in range(n_out):
            s, e = int(starts[i]), int(ends[i])
            e = max(e, s + 1)
            segs.append(jnp.max(arr.take(
                jnp.arange(s, e), axis=axis), axis=axis, keepdims=True))
        return jnp.concatenate(segs, axis=axis)

    out = a
    for d in range(ndim):
        out = pool_axis(out, 2 + d, *slices[d])
    return out


def _fractional_u(random_u):
    """The pseudo-random boundary offset: framework RNG when unset (the
    stochastic pooling the op exists for; fixed per trace under jit,
    fresh per call eagerly)."""
    if random_u is not None:
        return float(random_u)
    import jax
    from ...core import random as _r
    return float(jax.random.uniform(_r.next_key(), (),
                                    minval=0.05, maxval=0.95))


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """paddle.nn.functional.fractional_max_pool2d (3.0)."""
    u = _fractional_u(random_u)
    out = eager(lambda a: _fractional_pool(a, output_size, 2, u),
                (x,), {}, name="fractional_max_pool2d")
    return (out, None) if return_mask else out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    u = _fractional_u(random_u)
    out = eager(lambda a: _fractional_pool(a, output_size, 3, u),
                (x,), {}, name="fractional_max_pool3d")
    return (out, None) if return_mask else out
