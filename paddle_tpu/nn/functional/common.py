"""Common functionals: linear, dropout, embedding, normalize, interpolate,
pixel ops — python/paddle/nn/functional/common.py + input.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._registry import defop, as_array, eager
from ...core.tensor import Tensor
from ...core import random as prandom


def _linear_raw(x, weight, bias=None, name=None):
    # paddle weight layout is [in_features, out_features] (no transpose —
    # feeds the MXU directly as x @ w)
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def linear(x, weight, bias=None, name=None):
    if bias is None:
        return eager(_linear_raw, (x, weight), {}, name="linear")
    return eager(_linear_raw, (x, weight, bias), {}, name="linear")


def _dropout_raw(x, p, training, mode, key):
    if not training or p == 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)  # downscale_in_infer trains unscaled


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    key = prandom.next_key()
    if axis is not None:
        # broadcast mask along non-listed axes
        axes = [axis] if isinstance(axis, int) else list(axis)

        def raw(a):
            shape = [a.shape[i] if i in axes else 1 for i in range(a.ndim)]
            keep = 1.0 - p
            mask = jax.random.bernoulli(key, keep, tuple(shape))
            scale = 1.0 / keep if mode == "upscale_in_train" else 1.0
            return jnp.where(mask, a * scale, 0.0).astype(a.dtype)

        return eager(raw, (x,), {}, name="dropout")
    return eager(lambda a: _dropout_raw(a, p, training, mode, key), (x,), {},
                 name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    key = prandom.next_key()
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    alpha_p = -alpha * scale

    def raw(a):
        keep = 1.0 - p
        mask = jax.random.bernoulli(key, keep, a.shape)
        A = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
        B = -A * alpha_p * (1 - keep)
        return (A * jnp.where(mask, a, alpha_p) + B).astype(a.dtype)

    return eager(raw, (x,), {}, name="alpha_dropout")


def _embedding_raw(x, weight, padding_idx=None, sparse=False, name=None):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    idx = as_array(x)
    return eager(lambda w: _embedding_raw(idx, w, padding_idx), (weight,), {},
                 name="embedding")


def one_hot(x, num_classes, name=None):
    from ...core import dtype as dtypes
    return Tensor(jax.nn.one_hot(as_array(x), num_classes,
                                 dtype=dtypes.get_default_dtype()))


def _normalize_raw(x, p=2, axis=1, epsilon=1e-12, name=None):
    norm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True), 1.0 / p)
    return x / jnp.maximum(norm, epsilon)


normalize = defop("normalize", _normalize_raw)
cosine_similarity = defop("cosine_similarity", lambda x1, x2, axis=1, eps=1e-8, name=None:
                          _cos_sim_raw(x1, as_array(x2), axis, eps))


def _cos_sim_raw(x1, x2, axis, eps):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def _interpolate_raw(x, size=None, scale_factor=None, mode="nearest",
                     align_corners=False, align_mode=0, data_format="NCHW",
                     name=None):
    # NCHW assumed; NHWC handled by transpose
    chan_last = data_format in ("NHWC", "NWC", "NDHWC")
    if chan_last:
        x = jnp.moveaxis(x, -1, 1)
    spatial = x.shape[2:]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = tuple(int(s * f) for s, f in zip(spatial, scale_factor))
    else:
        size = tuple(int(v) for v in (size.numpy() if isinstance(size, Tensor) else size))
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
              "trilinear": "linear", "linear": "linear", "area": "linear"}[mode]
    out_shape = x.shape[:2] + size
    out = jax.image.resize(x, out_shape, method=method)
    if chan_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


interpolate = defop("interpolate", _interpolate_raw)
upsample = interpolate


def _pixel_shuffle_raw(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h * r, w * r, c // (r * r))


pixel_shuffle = defop("pixel_shuffle", _pixel_shuffle_raw)


def _pixel_unshuffle_raw(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = x.transpose(0, 1, 3, 5, 2, 4)
    return x.reshape(n, c * r * r, h // r, w // r)


pixel_unshuffle = defop("pixel_unshuffle", _pixel_unshuffle_raw)


def _channel_shuffle_raw(x, groups, data_format="NCHW", name=None):
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = x.transpose(0, 2, 1, 3, 4)
    return x.reshape(n, c, h, w)


channel_shuffle = defop("channel_shuffle", _channel_shuffle_raw)


def _unfold_raw(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    # im2col: [N, C, H, W] -> [N, C*kh*kw, L] — reference exposes this as
    # paddle.nn.functional.unfold; XLA's conv patch helper is the native path
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings) if not (isinstance(paddings, (list, tuple)) and len(paddings) == 4) else (0, 0)
    dh, dw = pair(dilations)
    if isinstance(paddings, (list, tuple)) and len(paddings) == 4:
        pads = [(paddings[0], paddings[1]), (paddings[2], paddings[3])]
    else:
        pads = [(ph, ph), (pw, pw)]
    n, c = x.shape[0], x.shape[1]
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), pads, rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, OH, OW]
    return patches.reshape(n, c * kh * kw, -1)


unfold = defop("unfold", _unfold_raw)


def _fold_raw(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)
    n, ckk, l = x.shape
    c = ckk // (kh * kw)
    ohh = (oh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    oww = (ow + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = x.reshape(n, c, kh, kw, ohh, oww)
    out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), dtype=x.dtype)
    for i in range(kh):
        for j in range(kw):
            hi = i * dh
            wj = j * dw
            out = out.at[:, :, hi:hi + sh * ohh:sh, wj:wj + sw * oww:sw].add(cols[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


fold = defop("fold", _fold_raw)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def raw(l):
        k = l.shape[-1]
        if prior_dist is not None:
            return (1 - epsilon) * l + epsilon * as_array(prior_dist)
        return (1 - epsilon) * l + epsilon / k

    return eager(raw, (label,), {}, name="label_smooth")


def _pairwise_distance_raw(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    d = x - y + epsilon
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p), axis=-1, keepdims=keepdim), 1.0 / p)


pairwise_distance = defop("pairwise_distance", lambda x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None:
                          _pairwise_distance_raw(x, as_array(y), p, epsilon, keepdim))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    from ...ops.manipulation import pad as _pad
    return _pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def _bilinear_raw(x1, x2, weight, bias=None, name=None):
    # out[n,o] = x1[n,i] W[o,i,j] x2[n,j] (+ b[o])
    out = jnp.einsum("ni,oij,nj->no", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def bilinear(x1, x2, weight, bias=None, name=None):
    return eager(_bilinear_raw, (x1, x2, weight, bias), {}, name="bilinear")


def _sequence_mask_raw(x, maxlen=None, dtype="int64"):
    ml = int(maxlen) if maxlen is not None else int(jnp.max(x))
    steps = jnp.arange(ml)
    mask = steps < x[..., None]
    return mask.astype(np.dtype(dtype) if dtype != "bool" else bool)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    return eager(lambda a: _sequence_mask_raw(a, maxlen, dtype), (x,), {},
                 name="sequence_mask")


def _temporal_shift_raw(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    # TSM: shift 1/ratio of channels one step along the segment axis
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate(
        [x5[:, 1:, :fold], jnp.zeros_like(x5[:, :1, :fold])], axis=1)
    right = jnp.concatenate(
        [jnp.zeros_like(x5[:, :1, fold:2 * fold]), x5[:, :-1, fold:2 * fold]],
        axis=1)
    out = jnp.concatenate([left, right, x5[:, :, 2 * fold:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    return eager(lambda a: _temporal_shift_raw(a, seg_num, shift_ratio,
                                               data_format), (x,), {},
                 name="temporal_shift")


def _affine_grid_raw(theta, out_shape, align_corners=True):
    n, _, h, w = [int(s) for s in out_shape]

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = axis_coords(h)
    xs = axis_coords(w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # H,W,3
    # (N,2,3) @ (H*W,3)^T → N,H,W,2
    out = jnp.einsum("nij,hwj->nhwi", theta.astype(jnp.float32), base)
    return out


def affine_grid(theta, out_shape, align_corners=True, name=None):
    return eager(lambda t: _affine_grid_raw(t, out_shape, align_corners),
                 (theta,), {}, name="affine_grid")


def _grid_sample_raw(x, grid, mode="bilinear", padding_mode="zeros",
                     align_corners=True):
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]

    def unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) * (size - 1) / 2.0
        return ((coord + 1.0) * size - 1.0) / 2.0

    fx = unnormalize(gx, w)
    fy = unnormalize(gy, h)

    def reflect(coord, lo, hi):
        rng = hi - lo
        coord = jnp.abs((coord - lo) % (2 * rng) - rng) + lo
        return coord

    if padding_mode == "reflection":
        if align_corners:
            fx = reflect(fx, 0.0, w - 1.0)
            fy = reflect(fy, 0.0, h - 1.0)
        else:
            fx = jnp.clip(reflect(fx, -0.5, w - 0.5), 0, w - 1)
            fy = jnp.clip(reflect(fy, -0.5, h - 0.5), 0, h - 1)

    def gather2d(ix, iy):
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        flat = x.reshape(n, c, h * w)
        lin = (iyc * w + ixc).reshape(n, -1)  # N,HW'
        vals = jnp.take_along_axis(flat, lin[:, None, :], axis=2)
        vals = vals.reshape((n, c) + ix.shape[1:])
        if padding_mode == "zeros":
            inb = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))
            vals = vals * inb[:, None].astype(vals.dtype)
        return vals

    if mode == "nearest":
        return gather2d(jnp.round(fx).astype(jnp.int32),
                        jnp.round(fy).astype(jnp.int32))
    x0 = jnp.floor(fx)
    y0 = jnp.floor(fy)
    wx = (fx - x0).astype(x.dtype)[:, None]
    wy = (fy - y0).astype(x.dtype)[:, None]
    x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
    v00 = gather2d(x0i, y0i)
    v01 = gather2d(x0i + 1, y0i)
    v10 = gather2d(x0i, y0i + 1)
    v11 = gather2d(x0i + 1, y0i + 1)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    return eager(lambda a, g: _grid_sample_raw(a, g, mode, padding_mode,
                                               align_corners), (x, grid), {},
                 name="grid_sample")


def _gather_tree_raw(ids, parents):
    # beam-search backtrace: ids/parents [T, N, B] → sequences re-threaded
    # through parent pointers, walked from the last step backward
    t, n, b = ids.shape

    def step(beams, inp):
        step_ids, step_parents = inp
        out = jnp.take_along_axis(step_ids, beams, axis=1)
        prev = jnp.take_along_axis(step_parents, beams, axis=1)
        return prev, out

    init = jnp.broadcast_to(jnp.arange(b, dtype=ids.dtype), (n, b))
    _, rev = jax.lax.scan(step, init, (ids[::-1], parents[::-1]))
    return rev[::-1]


def gather_tree(ids, parents):
    return eager(_gather_tree_raw, (ids, parents), {}, name="gather_tree")
