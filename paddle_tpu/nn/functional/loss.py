"""Loss functionals — python/paddle/nn/functional/loss.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._registry import defop, as_array, eager
from ...core.tensor import Tensor


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def _xent_raw(logits, label, weight=None, ignore_index=-100, reduction="mean",
              soft_label=False, axis=-1, label_smoothing=0.0):
    logp = jax.nn.log_softmax(logits, axis=axis)
    n_class = logits.shape[axis]
    if soft_label:
        soft = label
        if label_smoothing > 0.0:
            soft = soft * (1 - label_smoothing) + label_smoothing / n_class
        loss = -jnp.sum(soft * logp, axis=axis)
        mask = None
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        mask = (lbl != ignore_index)
        safe = jnp.where(mask, lbl, 0)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=axis)[..., 0] \
            if axis in (-1, logits.ndim - 1) else \
            jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
        if label_smoothing > 0.0:
            # smoothed target = (1-eps)*one_hot + eps/K
            smooth = jnp.mean(logp, axis=axis)
            loss = -((1 - label_smoothing) * picked + label_smoothing * smooth)
        else:
            loss = -picked
        if weight is not None:
            loss = loss * jnp.take(weight, safe)
        loss = jnp.where(mask, loss, 0.0)
    if reduction == "mean" and mask is not None:
        denom = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
        if weight is not None:
            safe = jnp.where(mask, label.astype(jnp.int32) if label.ndim == loss.ndim else 0, 0)
            denom = jnp.maximum(jnp.sum(jnp.where(mask, jnp.take(weight, safe), 0.0)), 1e-12)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    lbl = as_array(label)
    args = [input] + ([weight] if weight is not None else [])

    def raw(*a):
        w = a[1] if weight is not None else None
        return _xent_raw(a[0], lbl, w, ignore_index, reduction, soft_label,
                         axis, label_smoothing)

    if soft_label and isinstance(label, Tensor) and not label.stop_gradient:
        return eager(lambda x, l: _xent_raw(x, l, None, ignore_index, reduction,
                                            True, axis, label_smoothing),
                     (input, label), {}, name="cross_entropy")
    return eager(raw, tuple(args), {}, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle keeps the label dim on the loss
    if not soft_label:
        from .activation import softmax as _softmax
        lbl = as_array(label)
        if lbl.ndim == as_array(logits).ndim and lbl.shape[axis] == 1:
            pass
        else:
            loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return eager(lambda x, y: _reduce(jnp.square(x - y), reduction),
                 (input, label if isinstance(label, Tensor) else Tensor(jnp.asarray(label))),
                 {}, name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return eager(lambda x, y: _reduce(jnp.abs(x - y), reduction),
                 (input, label if isinstance(label, Tensor) else Tensor(jnp.asarray(label))),
                 {}, name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def raw(x, y):
        d = jnp.abs(x - y)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle's smooth_l1_loss multiplies by delta
        return _reduce(loss * delta, reduction)

    return eager(raw, (input, label), {}, name="smooth_l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    lbl = as_array(label).astype(jnp.int32)
    args = [input] + ([weight] if weight is not None else [])

    def raw(*a):
        logp = a[0]
        mask = lbl != ignore_index
        safe = jnp.where(mask, lbl, 0)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=1)[..., 0] \
            if logp.ndim == 2 else \
            jnp.take_along_axis(logp, safe[:, None], axis=1).squeeze(1)
        loss = -picked
        if weight is not None:
            wv = jnp.take(a[1], safe)
            loss = loss * wv
        loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(mask, jnp.take(a[1], safe), 0.0)) if weight is not None \
                else jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return eager(raw, tuple(args), {}, name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def raw(x, y, *w):
        eps = 1e-12
        loss = -(y * jnp.log(jnp.maximum(x, eps)) +
                 (1 - y) * jnp.log(jnp.maximum(1 - x, eps)))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return eager(raw, tuple(args), {}, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)

    def raw(x, y, *rest):
        # numerically stable: max(x,0) - x*y + log(1+exp(-|x|))
        loss = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        i = 0
        if pos_weight is not None:
            pw = rest[-1]
            logsig = jax.nn.log_sigmoid(x)
            logsig_neg = jax.nn.log_sigmoid(-x)
            loss = -(y * pw * logsig + (1 - y) * logsig_neg)
        if weight is not None:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    return eager(raw, tuple(args), {}, name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def raw(x, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - x)
        if reduction == "batchmean":
            return jnp.sum(loss) / x.shape[0]
        return _reduce(loss, reduction)

    return eager(raw, (input, label), {}, name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def raw(x, y, l):
        loss = jnp.maximum(-l * (x - y) + margin, 0.0)
        return _reduce(loss, reduction)

    return eager(raw, (input, other, label), {}, name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def raw(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0))
        return _reduce(loss, reduction)

    return eager(raw, (input, label), {}, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def raw(x1, x2, l):
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        loss = jnp.where(l == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)

    return eager(raw, (input1, input2, label), {}, name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def raw(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), -1), 1 / p)

        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return eager(raw, (input, positive, negative), {}, name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def raw(x, y):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if normalizer is not None:
            loss = loss / as_array(normalizer)
        return _reduce(loss, reduction)

    return eager(raw, (logit, label), {}, name="sigmoid_focal_loss")


def square_error_cost(input, label, name=None):
    return eager(lambda x, y: jnp.square(x - y), (input, label),
                 {}, name="square_error_cost")


_CTC_NEG_INF = -1e30  # -inf breeds nans through where/grad; huge-negative is safe


def _ctc_raw(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC forward algorithm (alpha recursion in log space) under lax.scan.

    log_probs: [T, N, C] log-softmax outputs; labels: [N, S] padded targets.
    Reference: phi fused warpctc kernel + python/paddle/nn/functional/loss.py
    ctc_loss (upstream-canonical, unverified — SURVEY.md §0); TPU-native as a
    compiled scan rather than a CPU/CUDA warpctc call.
    """
    t_max, n, _ = log_probs.shape
    s_max = labels.shape[1]
    labels = labels.astype(jnp.int32)
    input_lengths = input_lengths.astype(jnp.int32)
    label_lengths = label_lengths.astype(jnp.int32)

    # extended target sequence: blank, l1, blank, l2, ... blank  (2S+1)
    ext = jnp.full((n, 2 * s_max + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    positions = jnp.arange(2 * s_max + 1)[None, :]
    valid = positions < (2 * label_lengths[:, None] + 1)
    # s→s-2 skip allowed only onto a non-blank that differs from ext[s-2]
    skip_ok = jnp.concatenate(
        [jnp.zeros((n, 2), bool),
         (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], axis=1)

    def emit(lp_t):
        return jnp.take_along_axis(lp_t, ext, axis=1)  # [N, 2S+1]

    alpha0 = jnp.full((n, 2 * s_max + 1), _CTC_NEG_INF, log_probs.dtype)
    alpha0 = alpha0.at[:, 0:2].set(emit(log_probs[0])[:, 0:2])
    alpha0 = jnp.where(valid, alpha0, _CTC_NEG_INF)

    def logaddexp3(a, b, c):
        m = jnp.maximum(jnp.maximum(a, b), c)
        return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m) + jnp.exp(c - m))

    def step(alpha, inp):
        lp_t, t = inp
        prev1 = jnp.concatenate(
            [jnp.full((n, 1), _CTC_NEG_INF, alpha.dtype), alpha[:, :-1]], 1)
        prev2 = jnp.concatenate(
            [jnp.full((n, 2), _CTC_NEG_INF, alpha.dtype), alpha[:, :-2]], 1)
        prev2 = jnp.where(skip_ok, prev2, _CTC_NEG_INF)
        new = emit(lp_t) + logaddexp3(alpha, prev1, prev2)
        new = jnp.where(valid, new, _CTC_NEG_INF)
        # freeze alpha once past each sequence's input length
        new = jnp.where((t < input_lengths)[:, None], new, alpha)
        return new, None

    ts = jnp.arange(1, t_max)
    alpha, _ = jax.lax.scan(step, alpha0, (log_probs[1:], ts))

    # total log-likelihood: last blank (2L) + last label (2L-1)
    end = 2 * label_lengths
    a_end = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
    a_end1 = jnp.where(
        label_lengths > 0,
        jnp.take_along_axis(alpha, jnp.maximum(end - 1, 0)[:, None],
                            axis=1)[:, 0],
        _CTC_NEG_INF)
    ll = jnp.logaddexp(a_end, a_end1)
    loss = -ll
    if norm_by_times:
        loss = loss / input_lengths.astype(loss.dtype)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(label_lengths, 1)
                        .astype(loss.dtype))
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    return eager(lambda lp, lb, il, ll: _ctc_raw(lp, lb, il, ll, blank,
                                                 reduction, norm_by_times),
                 (log_probs, labels, input_lengths, label_lengths), {},
                 name="ctc_loss")


def _poisson_nll_raw(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:  # Stirling approximation for label! term
        stirling = label * jnp.log(label) - label + \
            0.5 * jnp.log(2 * jnp.pi * label)
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    return eager(lambda i, l: _poisson_nll_raw(i, l, log_input, full, epsilon,
                                               reduction), (input, label), {},
                 name="poisson_nll_loss")


def _gaussian_nll_raw(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    var = jnp.clip(variance, min=epsilon)
    loss = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
    if full:
        loss = loss + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, loss.dtype))
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    return eager(lambda i, l, v: _gaussian_nll_raw(i, l, v, full, epsilon,
                                                   reduction),
                 (input, label, variance), {}, name="gaussian_nll_loss")


def _dice_loss_raw(input, label, epsilon=1e-5):
    # input: [N, ..., C] probabilities; label: [N, ..., 1] class ids
    n_class = input.shape[-1]
    onehot = jax.nn.one_hot(jnp.squeeze(label, -1), n_class,
                            dtype=input.dtype)
    flat_i = input.reshape(input.shape[0], -1)
    flat_l = onehot.reshape(onehot.shape[0], -1)
    intersect = jnp.sum(flat_i * flat_l, axis=1)
    union = jnp.sum(flat_i, axis=1) + jnp.sum(flat_l, axis=1)
    return jnp.mean(1.0 - (2.0 * intersect + epsilon) / (union + epsilon))


def dice_loss(input, label, epsilon=1e-5, name=None):
    return eager(lambda i, l: _dice_loss_raw(i, l, epsilon), (input, label),
                 {}, name="dice_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    return eager(
        lambda i, l: -l * jnp.log(i + epsilon) -
        (1.0 - l) * jnp.log(1.0 - i + epsilon),
        (input, label), {}, name="log_loss")


def _npair_loss_raw(anchor, positive, labels, l2_reg=0.002):
    labels = labels.reshape(-1)
    same = (labels[:, None] == labels[None, :]).astype(anchor.dtype)
    target = same / jnp.sum(same, axis=1, keepdims=True)
    sim = anchor @ positive.T
    logp = jax.nn.log_softmax(sim, axis=1)
    xent = jnp.mean(jnp.sum(-target * logp, axis=1))
    reg = l2_reg * 0.25 * (jnp.mean(jnp.sum(anchor * anchor, axis=1)) +
                           jnp.mean(jnp.sum(positive * positive, axis=1)))
    return xent + reg


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    return eager(lambda a, p, l: _npair_loss_raw(a, p, l, l2_reg),
                 (anchor, positive, labels), {}, name="npair_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    # log(1+exp(-z)) = -log_sigmoid(z), the overflow-free form
    return eager(
        lambda i, l: _reduce(-jax.nn.log_sigmoid(l.astype(i.dtype) * i),
                             reduction),
        (input, label), {}, name="soft_margin_loss")


def _mlsm_raw(input, label, weight=None, reduction="mean"):
    l = label.astype(input.dtype)
    loss = -(l * jax.nn.log_sigmoid(input) +
             (1.0 - l) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    return eager(lambda i, l: _mlsm_raw(i, l, weight, reduction),
                 (input, label), {}, name="multi_label_soft_margin_loss")


def _multi_margin_raw(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    n, c = input.shape
    lbl = label.astype(jnp.int32).reshape(-1)
    correct = jnp.take_along_axis(input, lbl[:, None], axis=1)
    m = jnp.maximum(0.0, margin - correct + input) ** p
    if weight is not None:
        m = m * weight[lbl][:, None]
    m = m * (1.0 - jax.nn.one_hot(lbl, c, dtype=input.dtype))
    loss = jnp.sum(m, axis=1) / c
    return _reduce(loss, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    return eager(lambda i, l: _multi_margin_raw(i, l, p, margin,
                                                None if weight is None
                                                else as_array(weight),
                                                reduction),
                 (input, label), {}, name="multi_margin_loss")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def raw(i, l):
        d = i - l
        ad = jnp.abs(d)
        loss = jnp.where(ad <= delta, 0.5 * d * d,
                         delta * (ad - 0.5 * delta))
        return _reduce(loss, reduction)
    return eager(raw, (input, label), {}, name="huber_loss")


def _hsigmoid_raw(x, label, weight, bias, num_classes):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: phi hsigmoid_loss kernel / F.hsigmoid_loss). Heap-style
    node ids: leaves are label + num_classes; ancestors down to the root
    (id 1) are internal nodes whose row in `weight` is id - 1."""
    import numpy as _np
    label = label.reshape(-1)  # documented label shape is [N, 1]
    leaf = label.astype(jnp.int32) + num_classes
    depth = int(_np.ceil(_np.log2(2 * num_classes)))
    loss = jnp.zeros(x.shape[:1], jnp.float32)
    cur = leaf
    for _ in range(depth):
        parent = cur // 2
        bit = (cur % 2).astype(jnp.float32)      # which child was taken
        active = parent >= 1
        row = jnp.clip(parent - 1, 0, num_classes - 2)
        score = jnp.sum(x.astype(jnp.float32) * weight[row], axis=-1)
        if bias is not None:
            score = score + bias[row].astype(jnp.float32).reshape(-1)
        # BCE-with-logits against the path bit
        step = jnp.maximum(score, 0) - score * bit + jnp.log1p(
            jnp.exp(-jnp.abs(score)))
        loss = loss + jnp.where(active, step, 0.0)
        cur = parent
    return loss[:, None]


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """F.hsigmoid_loss parity (default tree only; custom path_table is the
    deliberately-deferred tier — SURVEY.md §7)."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "custom path_table/path_code hsigmoid is deferred "
            "(paddle_tpu/nn/functional/loss.py — default complete binary "
            "tree only)")
    from ...ops._registry import eager
    args = (input, label, weight) if bias is None else (input, label,
                                                        weight, bias)

    def raw(x, lab, w, b=None):
        return _hsigmoid_raw(x, lab, w, b, num_classes)

    return eager(raw, args, {}, name="hsigmoid_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """F.triplet_margin_with_distance_loss parity (custom metric form of
    triplet margin; default metric is euclidean)."""
    from ... import ops
    # default distance keeps an epsilon inside the sqrt: d sqrt(0) is
    # infinite and identical anchor/positive rows would NaN the grads
    # (same guard as triplet_margin_loss's |u - v| + eps)
    dist = distance_function or (
        lambda a, b: (((a - b) ** 2).sum(-1) + 1e-12).sqrt())
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = ops.minimum(dn, dist(positive, negative))
    from .activation import relu
    loss = relu(dp - dn + margin)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-class margin softmax (reference F.margin_cross_entropy over
    the margin_cross_entropy kernel): the label logit cos(theta) becomes
    cos(margin1*theta + margin2) - margin3, everything scaled by `scale`.
    Single-program form — the reference's model-parallel `group` argument
    is subsumed by GSPMD sharding of the class dim (SURVEY.md §2.3 TP row),
    so it is accepted and ignored."""
    from ... import ops

    def raw(lg, lab):
        lab = lab.reshape(-1).astype(jnp.int32)
        # clip strictly inside [-1, 1]: arccos' derivative is infinite at
        # the endpoints and a saturated label cosine would NaN the grads
        cos_t = jnp.clip(jnp.take_along_axis(
            lg, lab[:, None], axis=1)[:, 0], -1.0 + 1e-6, 1.0 - 1e-6)
        theta = jnp.arccos(cos_t)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        modified = lg.at[jnp.arange(lg.shape[0]), lab].set(target) * scale
        logp = jax.nn.log_softmax(modified, axis=-1)
        nll = -jnp.take_along_axis(logp, lab[:, None], axis=1)[:, 0]
        return nll, jnp.exp(logp)

    from ...ops._registry import eager
    loss, sm = eager(raw, (logits, label), {}, name="margin_cross_entropy")
    if reduction == "mean":
        loss = loss.mean()
    elif reduction == "sum":
        loss = loss.sum()
    return (loss, sm) if return_softmax else loss


def ctc_greedy_decoder(input, blank=0, name=None):
    """Greedy CTC decode (reference F.ctc_greedy_decoder): per-frame
    argmax, collapse repeats, drop blanks. input: [B, T, C] probs/logits.
    Returns (decoded [B, T] int64 padded with -1, lengths [B] int64)."""
    from ...ops._registry import eager

    def raw(x):
        ids = jnp.argmax(x, axis=-1)                        # [B, T]
        prev = jnp.concatenate(
            [jnp.full_like(ids[:, :1], -1), ids[:, :-1]], axis=1)
        keep = (ids != blank) & (ids != prev)               # collapse+drop
        # stable-compact kept tokens to the left via sort over masked keys
        B, T = ids.shape
        pos = jnp.where(keep, jnp.arange(T)[None, :], T + jnp.arange(T))
        order = jnp.argsort(pos, axis=1)
        compacted = jnp.take_along_axis(
            jnp.where(keep, ids, -1), order, axis=1)
        lengths = jnp.sum(keep, axis=1).astype(jnp.int64)
        return compacted.astype(jnp.int64), lengths

    return eager(raw, (input,), {}, name="ctc_greedy_decoder")


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Functional adaptive softmax (reference
    F.adaptive_log_softmax_with_loss): head_weight [in, cut0+n_clusters];
    tail_weights: per-cluster [down_proj [in, h], out_proj [h, size]]
    pairs; cutoffs excludes n_classes. Returns (target log-probs [N],
    mean NLL) like nn.AdaptiveLogSoftmaxWithLoss.forward."""
    from ... import ops
    cutlist = list(cutoffs)
    n_clusters = len(tail_weights)
    cut0 = cutlist[0]
    label = ops.reshape(label, [-1]).astype("int64")
    head_out = input.matmul(head_weight)
    if head_bias is not None:
        head_out = head_out + head_bias
    from .activation import log_softmax
    head_logp = log_softmax(head_out, axis=-1)
    clipped = ops.clip(label, 0, cut0 - 1)
    output = ops.take_along_axis(
        head_logp, ops.reshape(clipped, [-1, 1]), 1).reshape([-1])
    for i in range(n_clusters):
        lo = cutlist[i]
        size = int(tail_weights[i][1].shape[-1])
        hi = lo + size
        in_cluster = (label >= lo).logical_and(label < hi)
        rel = ops.clip(label - lo, 0, size - 1)
        proj = input.matmul(tail_weights[i][0]).matmul(tail_weights[i][1])
        c_logp = log_softmax(proj, axis=-1)
        val = head_logp[:, cut0 + i] + ops.take_along_axis(
            c_logp, ops.reshape(rel, [-1, 1]), 1).reshape([-1])
        output = ops.where(in_cluster, val, output)
    return output, -output.mean()


def _rnnt_raw(logits, labels, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean"):
    """RNN-Transducer loss (Graves 2012) as a compiled alpha recursion.

    logits: [B, T, U+1, V] joint-network outputs (U = max label length);
    labels: [B, U] int; input_lengths/label_lengths: [B].
    Reference: paddle.nn.functional.rnnt_loss wrapping the warp-transducer
    kernel (upstream python/paddle/nn/functional/loss.py — canonical,
    unverified, SURVEY.md §0). TPU-native: lax.scan over T with an inner
    scan over U for the same-frame label transitions — no host kernel.

    alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
                            alpha[t, u-1] + emit(t, u-1))
    loss = -(alpha[T-1, U] + blank(T-1, U)).

    fastemit_lambda applies FastEmit regularization as a (1 + λ) weight
    on the label-emission term of the recursion (the common sequence-
    level approximation of arXiv:2010.11148; exact warp-transducer
    FastEmit reweights gradients per-node, so values differ slightly
    for λ > 0 — λ = 0 is the textbook loss).
    """
    b, t_max, u1, _ = logits.shape
    u_max = u1 - 1
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    labels = labels.astype(jnp.int32)
    input_lengths = input_lengths.astype(jnp.int32)
    label_lengths = label_lengths.astype(jnp.int32)

    blank_lp = lp[..., blank]                                 # [B, T, U+1]
    lab = jnp.take_along_axis(
        lp[:, :, :u_max, :], labels[:, None, :, None], axis=3)[..., 0]
    lab = lab + np.log1p(fastemit_lambda)                     # [B, T, U]
    upos = jnp.arange(u1)[None, :]                            # [1, U+1]
    uvalid = upos <= label_lengths[:, None]                   # [B, U+1]

    def inner(alpha_prev_row, t_blank_prev, t_lab):
        # one time step: horizontal (label) transitions are a prefix
        # recurrence over u — scan it
        from_below = alpha_prev_row + t_blank_prev            # [B, U+1]

        def ustep(carry, inp):
            fb_u, lab_um1 = inp                               # [B], [B]
            a = jnp.logaddexp(fb_u, carry + lab_um1)
            return a, a

        a0 = from_below[:, 0]
        _, rest = jax.lax.scan(
            ustep, a0, (from_below[:, 1:].T, t_lab.T))
        return jnp.concatenate([a0[:, None], rest.T], axis=1)

    # t = 0 row: alpha[0, 0] = 0; alpha[0, u] = sum of label emissions
    zero = jnp.zeros((b, 1), jnp.float32)
    alpha0 = jnp.concatenate(
        [zero, jnp.cumsum(lab[:, 0], axis=1)], axis=1)
    alpha0 = jnp.where(uvalid, alpha0, _CTC_NEG_INF)

    def step(carry, t):
        alpha = carry
        new = inner(alpha, blank_lp[:, t - 1], lab[:, t])
        new = jnp.where(uvalid, new, _CTC_NEG_INF)
        new = jnp.where((t < input_lengths)[:, None], new, alpha)
        return new, new

    alpha_last, alphas = jax.lax.scan(
        step, alpha0, jnp.arange(1, t_max))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U+1]

    # per-sequence terminal: alpha[il-1, ll] + blank(il-1, ll)
    il = jnp.clip(input_lengths - 1, 0)
    a_fin = alphas[il, jnp.arange(b)]                         # [B, U+1]
    a_fin = jnp.take_along_axis(a_fin, label_lengths[:, None], 1)[:, 0]
    blank_fin = jnp.take_along_axis(
        blank_lp[jnp.arange(b), il], label_lengths[:, None], 1)[:, 0]
    nll = -(a_fin + blank_fin)
    if reduction == "mean":
        return jnp.mean(nll)
    if reduction == "sum":
        return jnp.sum(nll)
    return nll


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    return eager(lambda lg, lb, il, ll: _rnnt_raw(
        lg, lb, il, ll, blank, fastemit_lambda, reduction),
        (input, label, input_lengths, label_lengths), {}, name="rnnt_loss")
