"""Loss functionals — python/paddle/nn/functional/loss.py parity
(upstream-canonical, unverified — SURVEY.md §0)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._registry import defop, as_array, eager
from ...core.tensor import Tensor


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def _xent_raw(logits, label, weight=None, ignore_index=-100, reduction="mean",
              soft_label=False, axis=-1, label_smoothing=0.0):
    logp = jax.nn.log_softmax(logits, axis=axis)
    n_class = logits.shape[axis]
    if soft_label:
        soft = label
        if label_smoothing > 0.0:
            soft = soft * (1 - label_smoothing) + label_smoothing / n_class
        loss = -jnp.sum(soft * logp, axis=axis)
        mask = None
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        mask = (lbl != ignore_index)
        safe = jnp.where(mask, lbl, 0)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=axis)[..., 0] \
            if axis in (-1, logits.ndim - 1) else \
            jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
        if label_smoothing > 0.0:
            # smoothed target = (1-eps)*one_hot + eps/K
            smooth = jnp.mean(logp, axis=axis)
            loss = -((1 - label_smoothing) * picked + label_smoothing * smooth)
        else:
            loss = -picked
        if weight is not None:
            loss = loss * jnp.take(weight, safe)
        loss = jnp.where(mask, loss, 0.0)
    if reduction == "mean" and mask is not None:
        denom = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
        if weight is not None:
            safe = jnp.where(mask, label.astype(jnp.int32) if label.ndim == loss.ndim else 0, 0)
            denom = jnp.maximum(jnp.sum(jnp.where(mask, jnp.take(weight, safe), 0.0)), 1e-12)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    lbl = as_array(label)
    args = [input] + ([weight] if weight is not None else [])

    def raw(*a):
        w = a[1] if weight is not None else None
        return _xent_raw(a[0], lbl, w, ignore_index, reduction, soft_label,
                         axis, label_smoothing)

    if soft_label and isinstance(label, Tensor) and not label.stop_gradient:
        return eager(lambda x, l: _xent_raw(x, l, None, ignore_index, reduction,
                                            True, axis, label_smoothing),
                     (input, label), {}, name="cross_entropy")
    return eager(raw, tuple(args), {}, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle keeps the label dim on the loss
    if not soft_label:
        from .activation import softmax as _softmax
        lbl = as_array(label)
        if lbl.ndim == as_array(logits).ndim and lbl.shape[axis] == 1:
            pass
        else:
            loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return eager(lambda x, y: _reduce(jnp.square(x - y), reduction),
                 (input, label if isinstance(label, Tensor) else Tensor(jnp.asarray(label))),
                 {}, name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return eager(lambda x, y: _reduce(jnp.abs(x - y), reduction),
                 (input, label if isinstance(label, Tensor) else Tensor(jnp.asarray(label))),
                 {}, name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def raw(x, y):
        d = jnp.abs(x - y)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle's smooth_l1_loss multiplies by delta
        return _reduce(loss * delta, reduction)

    return eager(raw, (input, label), {}, name="smooth_l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    lbl = as_array(label).astype(jnp.int32)
    args = [input] + ([weight] if weight is not None else [])

    def raw(*a):
        logp = a[0]
        mask = lbl != ignore_index
        safe = jnp.where(mask, lbl, 0)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=1)[..., 0] \
            if logp.ndim == 2 else \
            jnp.take_along_axis(logp, safe[:, None], axis=1).squeeze(1)
        loss = -picked
        if weight is not None:
            wv = jnp.take(a[1], safe)
            loss = loss * wv
        loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(mask, jnp.take(a[1], safe), 0.0)) if weight is not None \
                else jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return eager(raw, tuple(args), {}, name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def raw(x, y, *w):
        eps = 1e-12
        loss = -(y * jnp.log(jnp.maximum(x, eps)) +
                 (1 - y) * jnp.log(jnp.maximum(1 - x, eps)))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return eager(raw, tuple(args), {}, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)

    def raw(x, y, *rest):
        # numerically stable: max(x,0) - x*y + log(1+exp(-|x|))
        loss = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        i = 0
        if pos_weight is not None:
            pw = rest[-1]
            logsig = jax.nn.log_sigmoid(x)
            logsig_neg = jax.nn.log_sigmoid(-x)
            loss = -(y * pw * logsig + (1 - y) * logsig_neg)
        if weight is not None:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    return eager(raw, tuple(args), {}, name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def raw(x, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - x)
        if reduction == "batchmean":
            return jnp.sum(loss) / x.shape[0]
        return _reduce(loss, reduction)

    return eager(raw, (input, label), {}, name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def raw(x, y, l):
        loss = jnp.maximum(-l * (x - y) + margin, 0.0)
        return _reduce(loss, reduction)

    return eager(raw, (input, other, label), {}, name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def raw(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0))
        return _reduce(loss, reduction)

    return eager(raw, (input, label), {}, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def raw(x1, x2, l):
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        loss = jnp.where(l == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)

    return eager(raw, (input1, input2, label), {}, name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def raw(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), -1), 1 / p)

        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return eager(raw, (input, positive, negative), {}, name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def raw(x, y):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if normalizer is not None:
            loss = loss / as_array(normalizer)
        return _reduce(loss, reduction)

    return eager(raw, (logit, label), {}, name="sigmoid_focal_loss")


def square_error_cost(input, label, name=None):
    return eager(lambda x, y: jnp.square(x - y), (input, label),
                 {}, name="square_error_cost")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    raise NotImplementedError(
        "ctc_loss: deferred (paddle_tpu/nn/functional/loss.py) — needs a "
        "lax.scan forward-backward; planned with the audio model family")
