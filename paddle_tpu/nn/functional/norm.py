"""Normalization functionals — python/paddle/nn/functional/norm.py parity
(upstream-canonical, unverified — SURVEY.md §0). The fused rms_norm/layer_norm
here are the jnp reference paths; paddle_tpu.kernels provides Pallas TPU
versions selected via FLAGS_use_pallas (reference analog:
paddle/phi/kernels/fusion/ fused norms)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._registry import defop, as_array, eager
from ...core.tensor import Tensor


def _layer_norm_raw(x, weight, bias, epsilon, begin_norm_axis):
    axes = tuple(range(begin_norm_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    begin = -len(tuple(normalized_shape))

    args, spec = [x], []
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)

    def raw(*a):
        xx = a[0]
        w = a[1] if weight is not None else None
        b = a[-1] if bias is not None else None
        return _layer_norm_raw(xx, w, b, epsilon, xx.ndim + begin)

    return eager(raw, tuple(args), {}, name="layer_norm")


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1,
             name=None):
    """RMSNorm — the reference ships this as a fused kernel
    (phi/kernels/fusion rms_norm); Pallas version in paddle_tpu.kernels."""
    from ...kernels import rms_norm as _k

    args = [x]
    if weight is not None:
        args.append(weight)

    def raw(*a):
        return _k.rms_norm_ref(a[0], a[1] if len(a) > 1 else None, epsilon)

    return eager(raw, tuple(args), {}, name="rms_norm")


def _batch_norm_raw(x, running_mean, running_var, weight, bias, training,
                    momentum, epsilon, data_format, use_batch_stats):
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    red = tuple(i for i in range(x.ndim) if i != c_axis)
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]
    if use_batch_stats:
        mean = jnp.mean(x, axis=red)
        var = jnp.var(x, axis=red)
    else:
        mean, var = running_mean, running_var
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, mean, var


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    use_batch_stats = training and not (use_global_stats is True)

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    rm = as_array(running_mean)
    rv = as_array(running_var)

    def raw(*a):
        xx = a[0]
        w = a[1] if weight is not None else None
        b = a[-1] if (bias is not None) else None
        out, _, _ = _batch_norm_raw(xx, rm, rv, w, b, training, momentum,
                                    epsilon, data_format, use_batch_stats)
        return out

    out = eager(raw, tuple(args), {}, name="batch_norm")

    if use_batch_stats and isinstance(running_mean, Tensor):
        # update running stats in place (paddle semantics: stats are buffers,
        # updated outside the grad tape)
        c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        red = tuple(i for i in range(x.ndim) if i != c_axis)
        xd = as_array(x)
        bm = jnp.mean(xd, axis=red)
        n = int(np.prod([xd.shape[i] for i in red]))
        bv = jnp.var(xd, axis=red) * (n / max(n - 1, 1))  # unbiased for running
        running_mean._rebind(momentum * rm + (1 - momentum) * bm)
        running_var._rebind(momentum * rv + (1 - momentum) * bv)
    return out


def _group_norm_raw(x, groups, weight, bias, epsilon, data_format):
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    if c_axis != 1:
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape((n, groups, c // groups) + spatial)
    red = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.var(xg, axis=red, keepdims=True)
    out = ((xg - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1, c] + [1] * len(spatial)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if c_axis != 1:
        out = jnp.moveaxis(out, 1, -1)
    return out


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)

    def raw(*a):
        w = a[1] if weight is not None else None
        b = a[-1] if bias is not None else None
        return _group_norm_raw(a[0], num_groups, w, b, epsilon, data_format)

    return eager(raw, tuple(args), {}, name="group_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)

    def raw(*a):
        xx = a[0]
        red = tuple(range(2, xx.ndim))
        mean = jnp.mean(xx, axis=red, keepdims=True)
        var = jnp.var(xx, axis=red, keepdims=True)
        out = (xx - mean) * jax.lax.rsqrt(var + eps)
        if weight is not None:
            shape = [1, xx.shape[1]] + [1] * (xx.ndim - 2)
            out = out * a[1].reshape(shape)
        if bias is not None:
            shape = [1, xx.shape[1]] + [1] * (xx.ndim - 2)
            out = out + a[-1].reshape(shape)
        return out

    return eager(raw, tuple(args), {}, name="instance_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def raw(a):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        sqp = jnp.pad(sq, pads)
        win = sum(jax.lax.slice_in_dim(sqp, i, i + c, axis=1) for i in range(size))
        return a / jnp.power(k + alpha * win / size, beta)

    return eager(raw, (x,), {}, name="local_response_norm")


def spectral_norm(weight, axis=0, power_iters=1, epsilon=1e-12, u=None,
                  name=None):
    """Spectral normalization: weight / sigma_max, sigma estimated by
    power iteration (reference F.spectral_norm over the spectral_norm
    kernel). `u` optionally seeds the left singular vector estimate (the
    SpectralNorm layer passes its persistent buffer); without it the
    iteration starts from a fixed normalized vector — more power_iters
    compensate."""
    import jax
    import jax.numpy as jnp
    from ...ops._registry import eager

    def raw(w, u0=None):
        h = w.shape[axis]
        mat = jnp.moveaxis(w, axis, 0).reshape(h, -1).astype(jnp.float32)
        if u0 is None:
            uv = jnp.ones((h,), jnp.float32) / jnp.sqrt(h * 1.0)
        else:
            uv = u0.reshape(h).astype(jnp.float32)
        for _ in range(max(power_iters, 1)):
            v = mat.T @ uv
            v = v / (jnp.linalg.norm(v) + epsilon)
            uv = mat @ v
            uv = uv / (jnp.linalg.norm(uv) + epsilon)
        sigma = uv @ mat @ v
        return (w / jnp.maximum(sigma, epsilon)).astype(w.dtype), \
            uv.astype(w.dtype)

    args = (weight,) if u is None else (weight, u)
    out, u_new = eager(raw, args, {}, name="spectral_norm")
    return (out, u_new) if u is not None else out
