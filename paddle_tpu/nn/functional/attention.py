"""Attention functionals — paddle.nn.functional.flash_attention +
scaled_dot_product_attention parity (reference: paddle/phi/kernels/fusion
flash_attn + python/paddle/nn/functional/flash_attention.py —
upstream-canonical, unverified, SURVEY.md §0)."""
from __future__ import annotations

import math

import jax.numpy as jnp

from ...ops._registry import eager, as_array
from ...kernels.flash_attention import flash_attention_fwd, mha_ref


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """q/k/v: [B, S, H, D] (paddle layout)."""
    if attn_mask is None and dropout_p == 0.0:
        return eager(lambda q, k, v: flash_attention_fwd(q, k, v, is_causal, None),
                     (query, key, value), {}, name="sdpa")

    mask = None if attn_mask is None else as_array(attn_mask)

    def raw(q, k, v):
        bias = None
        m = mask
        if m is not None and m.dtype != jnp.bool_:
            bias, m = m, None
        return mha_ref(q, k, v, causal=is_causal, bias=bias, mask=m)

    return eager(raw, (query, key, value), {}, name="sdpa")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    out = eager(lambda q, k, v: flash_attention_fwd(q, k, v, causal, None),
                (query, key, value), {}, name="flash_attention")
    return out, None  # (out, softmax) — softmax never materialized (flash)


def flash_attn_unpadded(*args, **kwargs):
    raise NotImplementedError(
        "flash_attn_unpadded (varlen): deferred — XLA prefers fixed shapes; "
        "pack ragged batches with attention masks instead "
        "(paddle_tpu/nn/functional/attention.py)")
