"""paddle_tpu.nn.functional — flat functional namespace (F.*).

Reference parity: python/paddle/nn/functional/ (upstream-canonical,
unverified — SURVEY.md §0)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403

from ...ops.manipulation import pad  # noqa: F401  (F.pad is the same op)
from ...ops.creation import one_hot  # noqa: F401
