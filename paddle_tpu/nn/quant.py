"""paddle.nn.quant — the quantization layer zoo.

Reference analog: python/paddle/nn/quant/quant_layers.py (FakeQuant*
observers + Quantized* wrapped layers used by the slim/QAT passes;
upstream-canonical, unverified — SURVEY.md §0, §2.4 quantization row).

TPU-native design: fake-quant is quantize-dequantize with a straight-
through estimator (quantization/__init__.py single-sources the math —
these classes are the paddle.nn.quant-shaped face over the same ops, so
nn.quant, paddle.quantization.QAT and the fake_quantize_* ops all agree
bit-for-bit). int8 matmuls stay simulated: the MXU computes bf16/int8
natively via XLA; a dedicated int8 kernel path is a perf project, not an
API gap.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import Layer
from ..ops._registry import REGISTRY
from ..quantization import (
    FakeQuanterWithAbsMax,
    QuantedConv2D,
    QuantedLinear,
    quant_dequant,
)


class FakeQuantAbsMax(Layer):
    """Per-tensor abs-max fake quantization (QAT observer+quant in one)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32"):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        out, _ = REGISTRY["fake_quantize_abs_max"](x,
                                                   bit_length=self.quant_bits)
        return out


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-output-channel abs-max fake quantization (weights)."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32"):
        super().__init__()
        self.quant_bits = quant_bits
        self.quant_axis = quant_axis

    def forward(self, x):
        out, _ = REGISTRY["fake_channel_wise_quantize_abs_max"](
            x, bit_length=self.quant_bits, quant_axis=self.quant_axis)
        return out


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation fake quant with a moving-average abs-max scale."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32"):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self.scale = self.create_parameter([1])
        self.scale.set_value(jnp.ones((1,), jnp.float32))
        self._accum = jnp.ones((1,), jnp.float32)
        self._state = jnp.ones((1,), jnp.float32)

    def forward(self, x):
        if not self.training:
            # inference quantizes on the CALIBRATED moving-average scale,
            # not the current batch's abs-max (review finding)
            return quant_dequant(x, self.scale, self.quant_bits)
        out, scale, accum, state = REGISTRY[
            "fake_quantize_moving_average_abs_max"](
            x, self.scale, self._accum, self._state,
            moving_rate=self.moving_rate, bit_length=self.quant_bits)
        self.scale.set_value(scale._data if hasattr(scale, "_data")
                             else scale)
        self._accum = accum._data if hasattr(accum, "_data") else accum
        self._state = state._data if hasattr(state, "_data") else state
        return out


class MovingAverageAbsMaxScale(Layer):
    """Observer-only: tracks the moving-average abs-max scale, passes x
    through unchanged (upstream's output-scale collector)."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__()
        self.moving_rate = moving_rate
        self.scale = self.create_parameter([1])
        self.scale.set_value(jnp.ones((1,), jnp.float32))

    def forward(self, x):
        if self.training:
            amax = jnp.max(jnp.abs(x._data)).reshape(1)
            new = (self.moving_rate * self.scale._data
                   + (1 - self.moving_rate) * amax)
            self.scale.set_value(new)
        return x


def _quant_bits(algo: str, bits=None) -> int:
    if bits is not None:
        return int(bits)
    if "int4" in algo:
        return 4
    return 8


def _raw(t):
    import jax.numpy as jnp
    return t._data if hasattr(t, "_data") else jnp.asarray(t)


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1,
                    bits=None):
    """paddle.nn.quant.weight_quantize: weight [Din, Dout] → (codes,
    scale). algo: weight_only_int8 / weight_only_int4 / llm.int8 (same
    int8 math at bf16 compute) / abs_max (legacy alias).

    Per-output-channel abs-max scales ([Dout]); group_size 64/128 gives
    group-wise scales ([Din/group_size, Dout]) like the upstream
    quantized_linear.py surface. arch (SM version) is meaningless on TPU
    and ignored. Upstream: python/paddle/nn/quant/quantized_linear.py."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    data = _raw(x)
    b = _quant_bits(algo, bits)
    bound = 2.0 ** (b - 1) - 1
    store = jnp.int4 if b == 4 else jnp.int8
    din, dout = data.shape
    if group_size and group_size > 0:
        if din % group_size:
            raise ValueError(f"group_size {group_size} must divide "
                             f"in_features {din}")
        g = data.reshape(din // group_size, group_size, dout)
        scale = jnp.maximum(jnp.max(jnp.abs(g), axis=1), 1e-9) / bound
        codes = jnp.clip(jnp.round(g / scale[:, None, :]), -bound, bound)
        codes = codes.reshape(din, dout).astype(store)
        return Tensor(codes), Tensor(scale.astype(jnp.float32))
    scale = jnp.maximum(jnp.max(jnp.abs(data), axis=0), 1e-9) / bound
    codes = jnp.clip(jnp.round(data / scale[None, :]), -bound, bound
                     ).astype(store)
    return Tensor(codes), Tensor(scale.astype(jnp.float32))


def _dequant(codes, scale, out_dtype):
    """codes [Din, Dout] + scale ([Dout] or [Din/g, Dout]) → weights."""
    import jax.numpy as jnp
    codes, scale = _raw(codes), _raw(scale)
    if scale.ndim == 2:  # group-wise
        din, dout = codes.shape
        g = din // scale.shape[0]
        w = codes.astype(out_dtype).reshape(scale.shape[0], g, dout) * \
            scale.astype(out_dtype)[:, None, :]
        return w.reshape(din, dout)
    return codes.astype(out_dtype) * scale.astype(out_dtype)[None, :]


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype=None):
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    dt = out_dtype or jnp.float32
    return Tensor(_dequant(x, scale, dt))


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """paddle.nn.quant.weight_only_linear: y = x @ dequant(weight) + bias.

    The dequantize (convert * scale) fuses into the matmul's operand read
    under XLA, so the codes stream from HBM at int8/int4 width — the
    TPU-native counterpart of the reference's fused weight-only CUDA
    kernels (VERDICT r4 missing 1). weight_dtype/arch/group_size keep the
    upstream signature; group layout is inferred from weight_scale's rank."""
    from ..core.tensor import Tensor
    xd = _raw(x)
    w = _dequant(weight, weight_scale, xd.dtype)
    y = xd @ w
    if bias is not None:
        y = y + _raw(bias)
    return Tensor(y)


def llm_int8_linear(x, w_int8, scale, threshold=6.0):
    """Weight-only int8 linear: dequantize-on-the-fly matmul (the XLA
    fusion keeps codes in HBM; outlier split is a no-op at bf16 compute)."""
    from ..core.tensor import Tensor
    return Tensor(_raw(x) @ _dequant(w_int8, scale, _raw(x).dtype))


class Stub(Layer):
    """paddle.nn.quant.Stub: placeholder the quantization passes replace
    with a configured observer; identity until converted."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x if self._observer is None else self._observer(x)


QuantStub = Stub
QuantizedLinear = QuantedLinear
QuantizedConv2D = QuantedConv2D

__all__ = [
    "FakeQuantAbsMax", "FakeQuantChannelWiseAbsMax",
    "FakeQuantMovingAverageAbsMax", "MovingAverageAbsMaxScale",
    "QuantedLinear", "QuantedConv2D", "QuantizedLinear", "QuantizedConv2D",
    "Stub", "QuantStub",
    "FakeQuanterWithAbsMax", "quant_dequant", "weight_quantize",
    "weight_dequantize", "weight_only_linear", "llm_int8_linear",
]
