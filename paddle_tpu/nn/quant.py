"""paddle.nn.quant — the quantization layer zoo.

Reference analog: python/paddle/nn/quant/quant_layers.py (FakeQuant*
observers + Quantized* wrapped layers used by the slim/QAT passes;
upstream-canonical, unverified — SURVEY.md §0, §2.4 quantization row).

TPU-native design: fake-quant is quantize-dequantize with a straight-
through estimator (quantization/__init__.py single-sources the math —
these classes are the paddle.nn.quant-shaped face over the same ops, so
nn.quant, paddle.quantization.QAT and the fake_quantize_* ops all agree
bit-for-bit). int8 matmuls stay simulated: the MXU computes bf16/int8
natively via XLA; a dedicated int8 kernel path is a perf project, not an
API gap.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import Layer
from ..ops._registry import REGISTRY
from ..quantization import (
    FakeQuanterWithAbsMax,
    QuantedConv2D,
    QuantedLinear,
    quant_dequant,
)


class FakeQuantAbsMax(Layer):
    """Per-tensor abs-max fake quantization (QAT observer+quant in one)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32"):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        out, _ = REGISTRY["fake_quantize_abs_max"](x,
                                                   bit_length=self.quant_bits)
        return out


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-output-channel abs-max fake quantization (weights)."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32"):
        super().__init__()
        self.quant_bits = quant_bits
        self.quant_axis = quant_axis

    def forward(self, x):
        out, _ = REGISTRY["fake_channel_wise_quantize_abs_max"](
            x, bit_length=self.quant_bits, quant_axis=self.quant_axis)
        return out


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation fake quant with a moving-average abs-max scale."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32"):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self.scale = self.create_parameter([1])
        self.scale.set_value(jnp.ones((1,), jnp.float32))
        self._accum = jnp.ones((1,), jnp.float32)
        self._state = jnp.ones((1,), jnp.float32)

    def forward(self, x):
        if not self.training:
            # inference quantizes on the CALIBRATED moving-average scale,
            # not the current batch's abs-max (review finding)
            return quant_dequant(x, self.scale, self.quant_bits)
        out, scale, accum, state = REGISTRY[
            "fake_quantize_moving_average_abs_max"](
            x, self.scale, self._accum, self._state,
            moving_rate=self.moving_rate, bit_length=self.quant_bits)
        self.scale.set_value(scale._data if hasattr(scale, "_data")
                             else scale)
        self._accum = accum._data if hasattr(accum, "_data") else accum
        self._state = state._data if hasattr(state, "_data") else state
        return out


class MovingAverageAbsMaxScale(Layer):
    """Observer-only: tracks the moving-average abs-max scale, passes x
    through unchanged (upstream's output-scale collector)."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__()
        self.moving_rate = moving_rate
        self.scale = self.create_parameter([1])
        self.scale.set_value(jnp.ones((1,), jnp.float32))

    def forward(self, x):
        if self.training:
            amax = jnp.max(jnp.abs(x._data)).reshape(1)
            new = (self.moving_rate * self.scale._data
                   + (1 - self.moving_rate) * amax)
            self.scale.set_value(new)
        return x


def weight_quantize(w, algo="abs_max", bits=8):
    """Quantize a weight tensor -> (int8 codes, scales) (paddle.nn.quant
    helper for weight-only serving)."""
    import jax.numpy as jnp
    data = w._data if hasattr(w, "_data") else jnp.asarray(w)
    bound = 2.0 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.max(jnp.abs(data), axis=0, keepdims=True),
                        1e-9) / bound
    codes = jnp.clip(jnp.round(data / scale), -bound - 1, bound
                     ).astype(jnp.int8)
    from ..core.tensor import Tensor
    return Tensor(codes), Tensor(scale)


def weight_dequantize(codes, scale):
    from ..core.tensor import Tensor
    return Tensor(codes._data.astype(scale._data.dtype) * scale._data)


def llm_int8_linear(x, w_int8, scale, threshold=6.0):
    """Weight-only int8 linear: dequantize-on-the-fly matmul (the XLA
    fusion keeps codes in HBM; outlier split is a no-op at bf16 compute)."""
    from ..core.tensor import Tensor
    w = w_int8._data.astype(x._data.dtype) * scale._data.astype(
        x._data.dtype)
    return Tensor(x._data @ w)


class Stub(Layer):
    """paddle.nn.quant.Stub: placeholder the quantization passes replace
    with a configured observer; identity until converted."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x if self._observer is None else self._observer(x)


QuantStub = Stub
QuantizedLinear = QuantedLinear
QuantizedConv2D = QuantedConv2D

__all__ = [
    "FakeQuantAbsMax", "FakeQuantChannelWiseAbsMax",
    "FakeQuantMovingAverageAbsMax", "MovingAverageAbsMaxScale",
    "QuantedLinear", "QuantedConv2D", "QuantizedLinear", "QuantizedConv2D",
    "Stub", "QuantStub",
    "FakeQuanterWithAbsMax", "quant_dequant", "weight_quantize",
    "weight_dequantize", "llm_int8_linear",
]
