"""Core layers: Linear/Embedding/Dropout/containers — parity with
python/paddle/nn/layer/{common,container}.py (upstream-canonical, unverified —
SURVEY.md §0)."""
from __future__ import annotations

from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from .layer import Layer, ParamAttr
from . import functional as F
from . import initializer as I


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """Weight layout [in_features, out_features] — feeds x @ w straight to
    the MXU with no transpose (reference keeps the same layout)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        if padding_idx is not None:
            with_pad = self.weight.numpy().copy()
            with_pad[padding_idx] = 0
            self.weight.set_value(with_pad)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class FeatureAlphaDropout(Layer):
    """Alpha dropout that drops whole channels (dim 1) — the SELU-safe
    counterpart of Dropout2D/3D (upstream paddle.nn.FeatureAlphaDropout)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        import numpy as np
        from ..core import random as _rnd
        import jax
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        alpha_p = -1.7580993408473766
        shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
        keep = jax.random.bernoulli(
            _rnd.next_key(), 1.0 - self.p, shape)
        a = (1.0 / np.sqrt((alpha_p ** 2 * self.p + 1) * (1 - self.p))
             ) if self.p < 1 else 0.0
        b = -a * alpha_p * self.p
        data = jnp.where(keep, x._data, alpha_p)
        return Tensor((a * data + b).astype(x._data.dtype))


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        new_shape = list(x.shape)
        new_shape[self.axis:self.axis + 1] = list(self.shape)
        return x.reshape(new_shape)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             data_format=self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, mode="bilinear", align_corners=True,
                         data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, mode="nearest",
                         data_format=data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


# ---- containers ------------------------------------------------------------

class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx if idx >= 0 else len(self) + idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, (dict, OrderedDict)) else sublayers
        for name, layer in items:
            self.add_sublayer(name, layer)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()


class Bilinear(Layer):
    """out = x1 · W · x2 + b (paddle.nn.Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.a)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.a = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.a)


class ZeroPad1D(Pad1D):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class ZeroPad3D(Pad3D):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)
