"""LLM serving path: save/load a generation-ready checkpoint and run
TP/DP-sharded prefill+decode behind the inference Config/Predictor API.

Reference analog: PaddleNLP `llm/` predict — `predictor.py` loading a
Llama checkpoint and serving model.generate() with mp>1 tensor
parallelism (upstream-canonical, unverified — SURVEY.md §0, §3.5, §1 Lx
row; VERDICT r2 missing item 1: training was multi-chip-complete,
inference was not).

TPU-native design: the artifact is the param pytree + config (no program
— generate() is re-traced and jit-compiled per shape signature, XLA is
the pass pipeline). Parallel serving is a mesh + infer_param_specs
placement: TP weights stay resident, the KV cache lives sharded over mp
heads for the whole compiled decode scan (nlp.generation.cache_spec).
"""
from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["save_llm", "load_llm", "LLMPredictor"]

LLM_SUFFIX = ".pdllm"


def _cfg_to_dict(cfg) -> Dict[str, Any]:
    d = dataclasses.asdict(cfg)
    for k in ("dtype", "param_dtype"):
        d[k] = jnp.dtype(d[k]).name
    return d


def _cfg_from_dict(d: Dict[str, Any]):
    from ..nlp import llama
    d = dict(d)
    for k in ("dtype", "param_dtype"):
        d[k] = jnp.dtype(d[k]).type
    return llama.LlamaConfig(**d)


def save_llm(path_prefix: str, params: Dict[str, Any], cfg) -> None:
    """Write `{prefix}.pdllm`: config + param pytree (numpy). The analog of
    the reference's .pdparams checkpoint plus its generation config.

    Format is pickle for .pdparams parity (paddle.save/load are
    pickle-based — SURVEY.md §5 checkpoint row), with the same caveat:
    NEVER load a .pdllm from an untrusted source (pickle executes code at
    load time). For exchange, convert to orbax via paddle_tpu.distributed
    .checkpoint."""
    payload = {
        "config": _cfg_to_dict(cfg),
        "params": jax.tree.map(np.asarray, params),
    }
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + LLM_SUFFIX, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)


def load_llm(path_prefix: str):
    with open(path_prefix + LLM_SUFFIX, "rb") as f:
        payload = pickle.load(f)
    return payload["params"], _cfg_from_dict(payload["config"])


class LLMPredictor:
    """Generation predictor with the paddle_infer handle API.

    Input handle: "input_ids" [B, P] int32. Output handle:
    "generated_ids" [B, max_new_tokens] int32. Decode knobs come from the
    Config (Config.enable_llm_generation / set_llm_parallel)."""

    def __init__(self, config):
        from ..nlp import llama
        if config._prefix is None:
            raise ValueError("Config has no model path")
        from ..nlp import generation
        params, cfg = load_llm(config._prefix)
        self._cfg = cfg
        self._config = config
        self._gen = dict(config._llm_gen or {})
        self._paged_stats = None
        self._paged_alloc = None
        wo = getattr(config, "_llm_weight_only", None)
        if wo:
            # quantize at load (host arrays): Config.enable_weight_only —
            # the serving counterpart of PaddleNLP --quant_type
            params = generation.quantize_for_serving(
                params, bits=4 if wo == "int4" else 8)
        mp = int(getattr(config, "_llm_mp", 1))
        dp = int(getattr(config, "_llm_dp", 1))
        self._mesh = None
        if mp * dp > 1:
            from ..parallel.topology import build_mesh
            ndev = len(jax.devices())
            if mp * dp > ndev:
                raise ValueError(
                    f"set_llm_parallel(mp={mp}, dp={dp}) needs {mp * dp} "
                    f"devices, have {ndev}")
            self._mesh = build_mesh(dp=dp, mp=mp,
                                    devices=jax.devices()[:mp * dp])
            from jax.sharding import NamedSharding
            specs = llama.infer_param_specs(cfg)
            if wo:
                specs = generation.quantized_specs(specs, params)
            # device_put the HOST (numpy) arrays straight into their shards
            # — staging jnp.asarray first would materialize every full
            # weight on device 0 and OOM models that only fit sharded
            self._params = jax.tree.map(
                lambda p, s: jax.device_put(
                    p, NamedSharding(self._mesh, s)),
                params, specs)
        else:
            self._params = jax.tree.map(jnp.asarray, params)
        self._feed: Dict[str, np.ndarray] = {}
        self._fetch: Dict[str, np.ndarray] = {}
        self._key = jax.random.PRNGKey(int(self._gen.get("seed", 0)))
        self._run_fn = None

    # -- handle API (paddle_infer::Predictor parity) -----------------------
    def get_input_names(self) -> List[str]:
        return ["input_ids"]

    def get_output_names(self) -> List[str]:
        return ["generated_ids"]

    def get_input_handle(self, name: str):
        from . import Tensor
        return Tensor(name, self, True)

    def get_output_handle(self, name: str):
        from . import Tensor
        return Tensor(name, self, False)

    def _fn(self):
        from ..nlp import generation
        g = self._gen
        greedy = g.get("decode_strategy", "greedy_search") == "greedy_search"
        kw = dict(max_new_tokens=int(g.get("max_new_tokens", 32)),
                  temperature=float(g.get("temperature", 1.0)),
                  top_k=int(g.get("top_k", 0)),
                  top_p=float(g.get("top_p", 1.0)), greedy=greedy,
                  eos_token_id=g.get("eos_token_id"),
                  pad_token_id=int(g.get("pad_token_id", 0)),
                  mesh=self._mesh)

        paged = getattr(self._config, "_llm_paged", None)
        if paged:
            from ..nlp import paged as paged_mod
            pad = kw["pad_token_id"]
            pkw = dict(max_new_tokens=kw["max_new_tokens"],
                       temperature=kw["temperature"], top_k=kw["top_k"],
                       top_p=kw["top_p"], greedy=kw["greedy"],
                       pad_token_id=pad,
                       block_size=paged["block_size"],
                       num_blocks=paged["num_blocks"])

            def run_paged(params, ids, key):
                import numpy as np
                lengths = np.maximum(
                    (np.asarray(ids) != pad).cumsum(1).max(1), 1)
                # ONE allocator persists across run() calls — later
                # admissions reuse the blocks earlier batches freed
                # (stats()["reused_blocks"] is the evidence). A batch
                # larger than everything seen so far grows the pool.
                B = ids.shape[0]
                bs = pkw["block_size"]
                need = B * -(-(int(lengths.max())
                               + pkw["max_new_tokens"]) // bs)
                alloc = self._paged_alloc
                if alloc is None or alloc.num_blocks < need:
                    cap = pkw["num_blocks"] or need
                    if cap < need:
                        raise ValueError(
                            f"enable_paged_kv(num_blocks={cap}) too small "
                            f"for this batch (needs {need} blocks)")
                    alloc = self._paged_alloc = (
                        paged_mod.BlockAllocator(cap))
                out, alloc, owned = paged_mod.paged_generate(
                    params, ids, lengths, self._cfg, key=key,
                    allocator=alloc,
                    **{k: v for k, v in pkw.items() if k != "num_blocks"})
                self._paged_stats = alloc.stats()
                for blocks in owned:   # request complete → blocks reusable
                    alloc.free(blocks)
                return out

            return run_paged

        def run(params, ids, key):
            return generation.generate(params, ids, self._cfg, key=key, **kw)

        return jax.jit(run)

    def run(self, inputs: Optional[List[np.ndarray]] = None
            ) -> List[np.ndarray]:
        if inputs is not None:
            self._feed["input_ids"] = np.asarray(inputs[0])
        ids = jnp.asarray(self._feed["input_ids"], jnp.int32)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            dp_total = self._mesh.shape["dp"] * self._mesh.shape["sharding"]
            if ids.shape[0] % dp_total:
                raise ValueError(
                    f"input_ids batch {ids.shape[0]} not divisible by the "
                    f"dp degree {dp_total} (set_llm_parallel); pad the "
                    f"request batch to a multiple of dp")
            ids = jax.device_put(
                ids, NamedSharding(self._mesh, P(("dp", "sharding"), None)))
        if self._run_fn is None:
            self._run_fn = self._fn()
        # fresh randomness per request, reproducible as a SEQUENCE from the
        # configured seed (greedy ignores the key entirely)
        self._key, sub = jax.random.split(self._key)
        out = np.asarray(self._run_fn(self._params, ids, sub))
        self._fetch = {"generated_ids": out}
        return [out]
