"""paddle.inference — Config / create_predictor facade.

Reference parity: paddle/fluid/inference/api (AnalysisPredictor,
paddle_infer::Config — upstream-canonical, unverified, SURVEY.md §0, §2.4
inference row, §3.5). TPU-native: there is no pass pipeline to rebuild —
the predictor wraps the jax.export artifact written by
paddle.static.save_inference_model; XLA is the analysis/fusion stack
(SURVEY.md §3.5 'TPU translation').
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Tensor", "Predictor", "create_predictor",
           "ContinuousBatcher", "PagedKVCache", "ServingEngine",
           "GenerationRequest"]


def __getattr__(name: str):
    # public serving surface without private module paths — delegated
    # to paddle_tpu.serving, which resolves each name lazily so
    # importing paddle_tpu.inference does not pull the nlp model stack
    if name in ("ContinuousBatcher", "PagedKVCache", "ServingEngine",
                "GenerationRequest"):
        from .. import serving
        return getattr(serving, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class Config:
    """paddle.inference.Config parity: points a Predictor at an exported
    model prefix (params are baked into the exported module)."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        # params are baked into the exported module; params_path kept for
        # API parity
        if model_path is not None and model_path.endswith(".pdmodel"):
            model_path = model_path[:-len(".pdmodel")]
        self._prefix = model_path
        self._device = "tpu"
        self._llm_gen = None
        self._llm_mp = 1
        self._llm_dp = 1
        self._llm_weight_only = None
        self._llm_paged = None

    def enable_llm_generation(self, max_new_tokens: int = 32,
                              decode_strategy: str = "greedy_search",
                              temperature: float = 1.0, top_k: int = 0,
                              top_p: float = 1.0, eos_token_id=None,
                              pad_token_id: int = 0, seed: int = 0):
        """Serve a .pdllm generation checkpoint (prefill + compiled decode
        scan) instead of a static .pdmodel artifact. Mirrors the PaddleNLP
        llm/ predict decode knobs (SURVEY.md §3.5)."""
        if decode_strategy not in ("greedy_search", "sampling"):
            raise ValueError(
                f"decode_strategy {decode_strategy!r} not supported: use "
                f"'greedy_search' or 'sampling' (beam_search is not "
                f"implemented in paddle_tpu.inference.llm)")
        self._llm_gen = dict(
            max_new_tokens=max_new_tokens, decode_strategy=decode_strategy,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_token_id=eos_token_id, pad_token_id=pad_token_id, seed=seed)

    def enable_weight_only(self, weight_dtype: str = "int8"):
        """Weight-only-quantized decode (the reference ecosystem's LLM
        serving default — PaddleNLP predict --quant_type weight_only_int8):
        the checkpoint's matmul weights are quantized at load to int8 (or
        int4) codes + per-channel scales and dequantized in-register, so
        decode streams weights at code width (VERDICT r4 missing 1)."""
        if weight_dtype not in ("int8", "int4"):
            raise ValueError(f"weight_dtype must be int8 or int4, got "
                             f"{weight_dtype!r}")
        self._llm_weight_only = weight_dtype

    def enable_paged_kv(self, block_size: int = 64,
                        num_blocks: Optional[int] = None):
        """Block-table KV cache for serving (reference: the fused
        block_multihead_attention + PaddleNLP serving's block pool —
        VERDICT r4 missing 2): requests of MIXED lengths share one block
        pool without T_max re-padding; per-request lengths are inferred
        as the non-pad prefix (pad_token_id from enable_llm_generation)."""
        self._llm_paged = dict(block_size=int(block_size),
                               num_blocks=num_blocks)

    def set_llm_parallel(self, mp: int = 1, dp: int = 1):
        """Tensor-/data-parallel serving degrees (reference: predictor
        --tensor_parallel_degree). Weights placed per infer_param_specs;
        the KV cache stays mp-sharded across the decode loop."""
        self._llm_mp, self._llm_dp = int(mp), int(dp)

    def set_prog_file(self, path: str):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def enable_use_gpu(self, memory_pool_mb=0, device_id=0):
        self._device = "tpu"  # accelerators are XLA's concern

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, *a, **k):
        pass  # XLA owns buffer reuse

    def switch_ir_optim(self, *a, **k):
        pass  # XLA owns the pass pipeline

    def set_cpu_math_library_num_threads(self, n):
        pass


class Tensor:
    """Input/output handle (paddle_infer::Tensor parity)."""

    def __init__(self, name: str, predictor: "Predictor", is_input: bool):
        self.name = name
        self._p = predictor
        self._is_input = is_input

    def copy_from_cpu(self, data: np.ndarray):
        self._p._feed[self.name] = np.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        return self._p._fetch[self.name]

    def shape(self):
        v = self._p._feed.get(self.name) if self._is_input else \
            self._p._fetch.get(self.name)
        return list(v.shape) if v is not None else None


class Predictor:
    """paddle.inference.Predictor parity: feed/run/fetch over a loaded
    inference program (see create_predictor)."""

    def __init__(self, config: Config):
        from ..static import load_inference_model, Executor
        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._prog, self._feed_names, self._fetch_names = \
            load_inference_model(config._prefix, Executor())
        self._feed: Dict[str, np.ndarray] = {}
        self._fetch: Dict[str, np.ndarray] = {}

    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(name, self, True)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            for n, v in zip(self._feed_names, inputs):
                self._feed[n] = np.asarray(v)
        outs = self._prog.run(self._feed, None)
        self._fetch = dict(zip(self._fetch_names, outs))
        return [self._fetch[n] for n in self._fetch_names]


def create_predictor(config: Config):
    """Dispatch: a Config pointing at a .pdllm generation checkpoint (or
    with enable_llm_generation set) gets the LLM serving predictor; plain
    .pdmodel artifacts get the jax.export Predictor."""
    import os
    from .llm import LLM_SUFFIX, LLMPredictor
    if config._llm_gen is not None or (
            config._prefix and os.path.exists(config._prefix + LLM_SUFFIX)):
        return LLMPredictor(config)
    return Predictor(config)
