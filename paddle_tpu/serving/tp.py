"""Tensor-parallel serving mesh — GSPMD sharding for the paged stack.

Reference analog: PaddleNLP `llm/` predict with mp_degree > 1 — the
Megatron-TP serving layout (qkv/gate/up column-split, o/down row-split,
the fused-attention KV cache sharded on its head axis) the reference
builds out of mpu layers (upstream-canonical, unverified — SURVEY.md
§3.5). Training already has this shape: `parallel/sharding.py` owns the
hybrid mesh and `llama.infer_param_specs` IS the serving TP table.

TPU-native design (ROADMAP direction 1): parallelism is not code —
GSPMD (arxiv 2105.04663) partitions the batcher's existing step
programs from sharding annotations on their INPUTS, so the fused,
quantized, speculative and disaggregated serving paths all go
multi-chip through one refactor. `MeshConfig` is the one knob: the
batcher builds a 1-D device mesh over the model axis, `device_put`s
weights and the paged KV pool to their shards at construction, and
AOT-lowers every step shape from sharded avals. The host-side
scheduler (block allocator, slot state, admission) is untouched:
slot/scheduler arrays are replicated, per-call host inputs are
uncommitted and auto-placed by dispatch, and XLA inserts the
collectives (activation all-gathers ahead of the o/down dots).

The one exception to "parallelism is not code" is the Pallas ragged
kernel: GSPMD cannot partition a pallas_call, so under a mesh the
step programs call it `shard_map`-wrapped over the head-sharded pool
(nlp/ragged_attention.py `_shard_specs`) — each device runs the
per-device kernel on its contiguous head shard and the head-axis
concat keeps the result bit-identical to the mesh-off kernel. The
speculative suffix-slab verify rides the same wrapper (the slab and
accept walk shard on heads naturally; slab visibility and the block
table stay replicated), and the verify's activation all-gather is the
same output-split convention below — so mesh x pallas x speculation
compose with greedy output still BIT-identical to the unsharded
batcher.

Unlike the training table (`llama.param_specs`) and the generation
table (`llama.infer_param_specs`), serving NEVER shards a contracted
dim: Megatron's o/down row split would make those matmuls per-shard
partials + a psum whose bf16 summation order differs from the
unsharded dot — ulp logit drift that flips near-tie argmaxes
mid-decode. Serving output-splits o/down instead, so every output
element is one full-contraction dot in the unsharded order and
greedy decode is BIT-identical to the mesh-off batcher (the gate
`bench_serving.py --tp` and tests/test_tp_serving.py enforce).

Sharding table (axis `mp`, TP degree t):

    weights   q/k/v/gate/up_proj   [L, Din, Dout]   P(None, None, mp)
              o/down_proj          [L, Din, Dout]   P(None, None, mp)
              '<w>:scale' (w8)     [L, 1,   Dout]   weight spec, the
                                                    contracted dim
                                                    forced replicated
              lm_head              [D, V]           P(None, mp)
              embed / norms                         replicated
    KV pool   k/v                  [L, N, bs, KV, hd]
                                   P(None, None, None, mp, None)
    scales    k/v int8 pool scales [L, N]           replicated (per-
                                   (layer, block) abs-max — no head
                                   axis to shard)
    scheduler table/lengths/slot state              replicated

Divisibility: t must divide num_attention_heads AND
num_key_value_heads (pool head axis; contiguous q-head shards then
align with their kv-head shard under GQA), intermediate_size
(gate/up/down), and vocab_size (lm_head column split).

CPU development recipe: set `XLA_FLAGS=--xla_force_host_platform_
device_count=N` BEFORE jax initializes and a single host exposes N
devices — `tests/test_tp_serving.py` and `bench_serving.py --tp` run
the whole TP matrix this way, no TPU required.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

# Sharded weight names (every projection is output-split — see the
# exactness note in `param_pspecs`); this list only drives the
# per-device byte accounting in `shard_info`.
_SHARDED_LAYER_KEYS = ("q_proj", "k_proj", "v_proj", "o_proj",
                       "gate_proj", "up_proj", "down_proj")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Serving-mesh description: a 1-D tensor-parallel device mesh.

    `tp` is the TP degree (device count), `axis` the mesh axis name
    every PartitionSpec refers to, `devices` an optional explicit
    tuple of `jax.devices()` indices (default: the first `tp`).
    Frozen + hashable: `.key()` rides every compiled-shape memo key
    (the KEY001-enforced convention), so two batchers that differ
    only in mesh layout can never serve each other's executables."""

    tp: int = 1
    axis: str = "mp"
    devices: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if int(self.tp) < 1:
            raise ValueError(f"tp degree must be >= 1, got {self.tp}")
        if not self.axis or not isinstance(self.axis, str):
            raise ValueError(f"axis must be a non-empty str, "
                             f"got {self.axis!r}")
        if self.devices is not None and len(self.devices) != self.tp:
            raise ValueError(
                f"devices names {len(self.devices)} device indices "
                f"but tp={self.tp}")

    def key(self) -> Tuple:
        """The memo-key element: mesh geometry + device assignment.
        Everything that changes the compiled program's partitioning
        is here; nothing else is (a key element never read under
        trace is a spurious-recompile storm — KEY001 kind b)."""
        return ("tp", int(self.tp), self.axis,
                self.devices if self.devices is None
                else tuple(int(d) for d in self.devices))

    def validate_for(self, cfg) -> None:
        """Fail fast on a geometry the sharding table can't split:
        every sharded dim must divide evenly (GSPMD would otherwise
        pad or refuse shapes mid-warmup, far from the misconfig)."""
        t = int(self.tp)
        for what, n in (("num_attention_heads", cfg.num_attention_heads),
                        ("num_key_value_heads", cfg.num_key_value_heads),
                        ("intermediate_size", cfg.intermediate_size),
                        ("vocab_size", cfg.vocab_size)):
            if n % t:
                raise ValueError(
                    f"tp={t} does not divide {what}={n} — every "
                    f"sharded dim must split evenly across the mesh")

    def build(self):
        """Construct the `jax.sharding.Mesh`, validated against the
        visible device set. CPU dev: force N host devices with
        XLA_FLAGS=--xla_force_host_platform_device_count=N before
        jax initializes."""
        import jax
        import numpy as np
        from jax.sharding import Mesh
        devs = jax.devices()
        if self.devices is not None:
            bad = [d for d in self.devices if not 0 <= d < len(devs)]
            if bad:
                raise ValueError(
                    f"device indices {bad} out of range — "
                    f"jax.devices() has {len(devs)} devices")
            picked = [devs[d] for d in self.devices]
        else:
            if len(devs) < self.tp:
                raise ValueError(
                    f"mesh wants tp={self.tp} devices but jax sees "
                    f"{len(devs)} — on CPU set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count="
                    f"{self.tp} before jax initializes")
            picked = devs[:self.tp]
        return Mesh(np.array(picked), (self.axis,))

    def describe(self) -> Dict[str, Any]:
        """Attribution stamp for snapshot()/health()/trace_report:
        mesh shape + the platform it landed on."""
        return {"tp": int(self.tp), "axis": self.axis,
                "devices": (list(range(self.tp))
                            if self.devices is None
                            else [int(d) for d in self.devices])}


def param_pspecs(cfg, params) -> Dict[str, Any]:
    """PartitionSpec tree for a serving param tree on axis 'mp' —
    `llama.infer_param_specs` (no ZeRO axis: weights stay resident so
    decode inserts no per-step param all-gathers) with the serving
    exactness override below, extended over weight-only-quantized
    ':scale' leaves via `generation.quantized_specs`."""
    from jax.sharding import PartitionSpec as P
    from ..nlp import llama
    from ..nlp.generation import quantized_specs
    specs = llama.infer_param_specs(cfg)
    # Serving invariant: greedy output must be BIT-identical to the
    # unsharded batcher. Megatron row-splits o/down on the CONTRACTED
    # dim, which turns each matmul into per-shard partials + a psum
    # whose bf16 summation order differs from the unsharded dot — ulp
    # drift, enough to flip a near-tie argmax mid-decode. Serving
    # output-splits them instead: GSPMD all-gathers the (head/ffn-
    # sharded) activations and every output element is one
    # full-contraction dot in the unsharded order. Trades the psum for
    # an activation all-gather and keeps every weight sharded.
    specs["layers"]["o_proj"] = P(None, None, "mp")
    specs["layers"]["down_proj"] = P(None, None, "mp")
    if any(k.endswith(":scale") for k in params["layers"]):
        specs = quantized_specs(specs, params)
    return specs


def _rename_axis(spec, new: str):
    """Rewrite a PartitionSpec's 'mp' entries to the mesh's axis name
    (identity for the default axis)."""
    from jax.sharding import PartitionSpec as P
    return P(*[new if a == "mp" else a for a in spec])


def build_shardings(mesh_cfg: MeshConfig, cfg, params):
    """(mesh, param sharding tree, pool sharding, replicated sharding)
    — everything the batcher pins at construction and lowers from.
    The KV pool shards on its head axis (dim 3 of [L, N, bs, KV, hd]);
    the int8 scale pools, block table and slot arrays are replicated
    (see the module sharding table)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh_cfg.validate_for(cfg)
    mesh = mesh_cfg.build()
    ax = mesh_cfg.axis
    pspecs = jax.tree_util.tree_map(
        lambda s: _rename_axis(s, ax), param_pspecs(cfg, params),
        is_leaf=lambda x: isinstance(x, P))
    shard_params = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspecs,
        is_leaf=lambda x: isinstance(x, P))
    shard_pool = NamedSharding(mesh, P(None, None, None, ax, None))
    shard_repl = NamedSharding(mesh, P())
    return mesh, shard_params, shard_pool, shard_repl


def shard_info(mesh_cfg: MeshConfig, batcher) -> Dict[str, Any]:
    """The observability stamp: mesh shape plus PER-DEVICE byte
    accounting — the pool's K/V tensors split by tp (head-axis
    shards), the int8 scale pools and scheduler state replicated, so
    per-device bytes = scales + (pool - scales)/tp. trace_report's
    replica column attributes multi-chip replicas from this. The mesh
    dict carries the replica's RESOLVED fast-path backends
    (attention_impl, spec_backend) so a fleet operator can see which
    replicas actually run the kernel/spec paths, not just which were
    asked to."""
    t = int(mesh_cfg.tp)
    total = batcher.kv_pool_bytes()
    scales = 0
    c = batcher.cache
    if c.k_scale is not None:
        scales = int(c.k_scale.nbytes + c.v_scale.nbytes)
    per_dev = scales + (total - scales) // t
    sharded_w = 0
    layers = batcher.params["layers"]
    for name in _SHARDED_LAYER_KEYS:
        sharded_w += int(layers[name].nbytes)
    if "lm_head" in batcher.params:
        sharded_w += int(batcher.params["lm_head"].nbytes)
    w_total = batcher.weight_bytes()
    mesh_d = mesh_cfg.describe()
    mesh_d["attention_impl"] = batcher.attention_impl
    mesh_d["spec_backend"] = (batcher.spec_attention_impl
                              if batcher.speculative else None)
    return {
        "mesh": mesh_d,
        "kv_pool_bytes_per_device": per_dev,
        "weight_bytes_per_device":
            (w_total - sharded_w) + sharded_w // t,
    }
