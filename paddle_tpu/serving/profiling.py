"""paddle_tpu.serving.profiling — sampled device-time attribution for
the continuous batcher.

The PR 7 trace timelines attribute per-chunk time as HOST wall per
call — which, with async dispatch, measures how long the host took to
*issue* the work, not how long the device took to *do* it. A TTFT
regression could therefore be the Pallas ragged kernel, the XLA
fallback, or host-side scheduling, and the timeline could not say
which. This module closes that gap two ways:

  * **Sampled steps** — every Nth step tick (``sample_every``, default
    64; 0 disables) the batcher wraps the already-issued device call
    with a ``jax.block_until_ready`` fence and records the measured
    device wall per shape key ``(mode, bucket, units, impl,
    weight_dtype, kv_dtype)`` into bounded per-shape histograms. One
    fenced step in N costs ~1/N of a step of extra latency on the
    sampled tick and NOTHING on the other N-1 (the sample gate is the
    documented SYNC001 exception: the fence never runs in the unfenced
    path, and the compiled-shape memo keys never see the profiler).
  * **Capture windows** — ``arm_capture(steps=K)`` fences the next K
    ticks unconditionally and retains one record per fenced step
    (mode, composition, host vs device wall). The engine merges those
    spans (and per-chunk ``device_dur`` annotations) back into the
    TraceSink so ``to_chrome_trace()`` timelines carry device wall
    next to host wall, and ``ServingEngine.capture_profile()`` /
    ``POST /debug/profile`` return the report over HTTP.

Attribution convention: ``host_s`` is dispatch wall (the device call
returning control to the host — enqueue cost), ``device_s`` is
call-start to fence-completion (everything the step put on the
device, drained). On an async backend ``device_s >= host_s`` and the
difference is the device-side remainder the old timelines could not
see; on CPU jax the two nearly coincide — the *fields* are what make
regressions attributable.

Dependency-free on purpose (stdlib only, like `serving.trace` and
`serving.slo`): the batcher owns the jax fence; this module only does
host-side counting, so `tools/trace_report.py` and the tests can
reason about reports without jax.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["StepProfiler"]

# bounds: distinct shape keys retained (beyond: counted, not stored)
# and raw device-wall samples kept per key for percentile math
_MAX_KEYS = 64
_MAX_SAMPLES_PER_KEY = 512


class _ShapeStats:
    """Bounded per-shape accumulator: exact count/sum, ring of recent
    device-wall samples for percentiles."""

    __slots__ = ("count", "device_sum_s", "host_sum_s", "ring")

    def __init__(self):
        self.count = 0
        self.device_sum_s = 0.0
        self.host_sum_s = 0.0
        self.ring: List[float] = []

    def add(self, device_s: float, host_s: float) -> None:
        if len(self.ring) < _MAX_SAMPLES_PER_KEY:
            self.ring.append(device_s)
        else:
            self.ring[self.count % _MAX_SAMPLES_PER_KEY] = device_s
        self.count += 1
        self.device_sum_s += device_s
        self.host_sum_s += host_s

    def summary(self) -> Dict[str, float]:
        s = sorted(self.ring)

        def pct(q):
            return s[min(len(s) - 1,
                         max(0, int(round(q * (len(s) - 1)))))]
        return {
            "count": self.count,
            "device_sum_s": self.device_sum_s,
            "host_sum_s": self.host_sum_s,
            "device_mean_s": self.device_sum_s / self.count,
            "device_p50_s": pct(0.50),
            "device_p99_s": pct(0.99),
        }


class StepProfiler:
    """Sampled device-time profiler for `ContinuousBatcher` step ticks.

    The batcher asks `should_fence()` once per device-call tick; True
    means "fence THIS call and report the measurement" — every
    `sample_every`th tick, plus every tick of an armed capture window.
    After fencing it calls `record(...)` with the measured walls and
    the tick's shape key; capture-window ticks additionally retain a
    per-step record for timeline merging. All host-side arithmetic
    under one lock; `arm_capture` is callable from any thread (the
    engine's `capture_profile` and the frontend's `/debug/profile`
    arm it while the engine thread steps).
    """

    def __init__(self, sample_every: int = 64):
        if int(sample_every) < 0:
            raise ValueError("sample_every must be >= 0 (0 disables)")
        self.sample_every = int(sample_every)
        self._lock = threading.Lock()
        self._tick = 0          # device-call ticks seen
        self.samples = 0        # fenced ticks measured
        self.dropped_keys = 0   # shapes past the retention bound
        self._shapes: Dict[Tuple, _ShapeStats] = {}
        # capture window: ticks remaining + retained per-step records
        self._capture_left = 0
        self._capture_steps: List[Dict[str, Any]] = []
        self._capture_total = 0
        self._capture_cancelled = False

    # ---- the per-tick gate (hot path: one int compare in the common
    #      unfenced case) -------------------------------------------------
    def should_fence(self) -> bool:
        """Advance the tick counter and decide whether the batcher
        fences THIS device call: every `sample_every`th tick, or any
        tick while a capture window is armed. The unfenced path costs
        one locked increment and compare — nothing touches the device."""
        with self._lock:
            self._tick += 1
            if self._capture_left > 0:
                return True
            return (self.sample_every > 0
                    and self._tick % self.sample_every == 0)

    def record(self, *, mode: str, bucket: int, units: int, impl: str,
               weight_dtype: str, kv_dtype: str, device_s: float,
               host_s: float, detail: Optional[Dict] = None) -> bool:
        """One fenced tick's measurement, attributed to its shape key.
        `detail` (rids/unit composition) is retained only for capture-
        window steps. Returns True when this record CLOSED an armed
        capture window (the waiter's wake-up signal)."""
        key = (mode, int(bucket), int(units), impl, weight_dtype,
               kv_dtype)
        with self._lock:
            self.samples += 1
            stats = self._shapes.get(key)
            if stats is None:
                if len(self._shapes) >= _MAX_KEYS:
                    self.dropped_keys += 1
                else:
                    stats = self._shapes[key] = _ShapeStats()
            if stats is not None:
                stats.add(float(device_s), float(host_s))
            if self._capture_left > 0:
                self._capture_left -= 1
                self._capture_steps.append({
                    "mode": mode, "bucket": int(bucket),
                    "units": int(units), "impl": impl,
                    "weight_dtype": weight_dtype, "kv_dtype": kv_dtype,
                    "device_s": float(device_s),
                    "host_s": float(host_s),
                    **(detail or {})})
                return self._capture_left == 0
            return False

    # ---- capture windows -------------------------------------------------
    def arm_capture(self, steps: int) -> None:
        """Fence the next `steps` ticks unconditionally and retain one
        record per fenced step. Re-arming extends an open window;
        records of a previous completed window are replaced."""
        if int(steps) < 1:
            raise ValueError("capture steps must be >= 1")
        with self._lock:
            if self._capture_left == 0:
                self._capture_steps = []
                self._capture_total = 0
            self._capture_left += int(steps)
            self._capture_total += int(steps)
            self._capture_cancelled = False

    def capture_active(self) -> bool:
        """True while an armed capture window still has ticks to fence."""
        with self._lock:
            return self._capture_left > 0

    def cancel_capture(self) -> int:
        """Disarm an open capture window (already-captured step
        records are kept; the report's `complete` stays False).
        Returns the number of fences cancelled. A waiter that gave up
        (`capture_profile` timeout) MUST call this — a leftover armed
        window would silently fence every future tick once traffic
        resumes, a latency tax nobody asked for."""
        with self._lock:
            left, self._capture_left = self._capture_left, 0
            if left:
                self._capture_cancelled = True
            return left

    def capture_report(self) -> Dict[str, Any]:
        """The last capture window: per-step records (mode,
        composition, host vs device wall) plus completion state —
        `complete` False means the window was still armed when read
        (an idle engine produces no ticks to fence)."""
        with self._lock:
            return {
                "steps_requested": self._capture_total,
                "steps_captured": len(self._capture_steps),
                "complete": (self._capture_total > 0
                             and self._capture_left == 0
                             and not self._capture_cancelled),
                "steps": [dict(s) for s in self._capture_steps],
            }

    # ---- reporting -------------------------------------------------------
    @staticmethod
    def key_fields(key: Tuple) -> Dict[str, Any]:
        """A shape key tuple as named fields (the report's row schema)."""
        mode, bucket, units, impl, wd, kd = key
        return {"mode": mode, "bucket": bucket, "units": units,
                "impl": impl, "weight_dtype": wd, "kv_dtype": kd}

    def report(self) -> Dict[str, Any]:
        """Everything measured so far: the sampling config, per-shape
        device-wall histograms (count / sums / p50 / p99 keyed by the
        (mode, bucket, units, impl, qkey) fields) and the last capture
        window. JSON-safe — `/debug/profile` returns exactly this."""
        with self._lock:
            shapes = [{**self.key_fields(k), **v.summary()}
                      for k, v in self._shapes.items()]
            ticks, samples = self._tick, self.samples
            dropped = self.dropped_keys
        shapes.sort(key=lambda r: -r["device_sum_s"])
        return {
            "sample_every": self.sample_every,
            "ticks": ticks,
            "samples": samples,
            "dropped_keys": dropped,
            "shapes": shapes,
            "capture": self.capture_report(),
        }
