"""paddle_tpu.serving — async request-serving engine over the paged-KV
continuous batcher.

The host-side serving layer the ROADMAP north star calls for: a
thread-backed `ServingEngine` owns a `ContinuousBatcher`
(`paddle_tpu.nlp.paged`) and keeps its in-flight batch saturated from a
bounded priority queue, with per-request lifecycle (deadlines,
cancellation, per-request stop tokens / budgets), streaming output
channels, lock-safe metrics, and a step-level exception boundary that
fails only the affected requests.

    from paddle_tpu import serving

    eng = serving.ServingEngine(params, cfg, max_batch=4,
                                block_size=16, max_total_len=512,
                                max_new_tokens=64)
    out = eng.generate(prompt_ids)                   # blocking
    for tok in eng.stream(prompt_ids):               # incremental
        ...
    req = eng.submit(prompt_ids, priority=1, timeout_s=30.0,
                     stop_token_id=eos)              # async handle
    print(eng.snapshot())                            # metrics + pool
    eng.shutdown()                                   # graceful drain

Modules: `engine` (ServingEngine loop), `request` (lifecycle/channels),
`scheduler` (admission queue: priority + FIFO + aging + backpressure),
`metrics` (counters/gauges/histograms + profiler-span timers +
Prometheus text exposition via `MetricsRegistry.to_prometheus()`),
`cache` (automatic prefix cache: trie index over shared KV blocks,
refcounted by `RefcountingBlockAllocator` — on by default; pass
`prefix_cache=False` to serve cold), `trace` (per-request trace
timelines with Chrome-trace/Perfetto export + the step flight
recorder the engine dumps on a device-step failure), `faults`
(deterministic fault injection: the chaos harness behind the engine's
quarantine / retry / watchdog recovery paths and
`bench_serving.py --chaos`), `router` (N-replica routing: health +
occupancy + prefix-affinity policy, cross-replica failover via
resume-from-`prompt + tokens`), `supervisor` (self-healing replica
lifecycle: auto-restart with a readiness gate, exponential backoff
and a crash-loop circuit breaker — `Router(auto_restart=True)`),
`kvtransfer` (portable per-request KV-block snapshots: the
dependency-free `KVSnapshot` container behind
`ContinuousBatcher.export_kv`/`import_kv` — disaggregated
prefill/decode handoff via `Router(disaggregated=True)` +
`ServingEngine(role="prefill"|"decode")`, warm failover, and
supervisor drain-export-respawn-resume), `frontend` (stdlib asyncio
HTTP: `POST /v1/generate`,
`POST /v1/stream` SSE, `GET /health`, `GET /metrics` with
per-replica labels, `POST /admin/reset_breaker`,
`POST /debug/profile`), `slo` (the SLO engine: declarative
objectives evaluated over dual rolling windows into burn rates and
OK/WARN/BREACH verdicts — `health()["slo"]`, `slo_burn_rate_*`
gauges, `slo_breaches_total` counters, fleet rollup in the Router),
`profiling` (sampled device-time attribution: every Nth step fenced
with block_until_ready into per-shape device-wall histograms, plus
on-demand capture windows whose device spans land in the trace
timelines), `speculative` (self-speculative decoding config +
acceptance accounting: the draft-and-verify pipeline behind
`ServingEngine(speculative=True, spec_k=, draft_layers=)` — a
truncated-layer draft proposes k tokens, the target verifies all k+1
positions in one paged call and commits only accepted rows, so greedy
output is provably identical to plain decode while tokens/step
multiplies).
"""
from __future__ import annotations

from .cache import PrefixCacheIndex  # noqa: F401
from .faults import FaultInjector, InjectedFault  # noqa: F401
from .metrics import Counter, Gauge, Histogram, MetricsRegistry  # noqa: F401
from .request import (  # noqa: F401
    GenerationRequest, RequestState, TERMINAL_STATES,
    RequestError, RequestCancelled, RequestFailed, RequestTimedOut,
)
from .profiling import StepProfiler  # noqa: F401
from .scheduler import AdmissionQueue, QueueFullError  # noqa: F401
from .speculative import SpecConfig, SpecStats  # noqa: F401
from .slo import SloTracker, DEFAULT_OBJECTIVES  # noqa: F401
from .kvtransfer import KVSnapshot  # noqa: F401
from .trace import TraceSink, FlightRecorder  # noqa: F401

__all__ = [
    "ServingEngine", "EngineStopped", "HungStepError",
    "GenerationRequest", "RequestState", "TERMINAL_STATES",
    "RequestError", "RequestCancelled", "RequestFailed", "RequestTimedOut",
    "AdmissionQueue", "QueueFullError",
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "TraceSink", "FlightRecorder",
    "SloTracker", "StepProfiler",
    "SpecConfig", "SpecStats",
    "KVSnapshot",
    "FaultInjector", "InjectedFault",
    "PrefixCacheIndex", "RefcountingBlockAllocator",
    "ContinuousBatcher", "PagedKVCache",
    "Router", "NoReplicaAvailable", "default_policy", "HttpFrontend",
    "ReplicaSupervisor",
]


def __getattr__(name: str):
    # ServingEngine pulls the nlp model stack — resolve lazily so plain
    # `import paddle_tpu` (which imports this package) stays light
    if name in ("ServingEngine", "EngineStopped", "HungStepError"):
        from . import engine
        return getattr(engine, name)
    if name in ("Router", "NoReplicaAvailable", "default_policy"):
        from . import router
        return getattr(router, name)
    if name == "HttpFrontend":
        from . import frontend
        return getattr(frontend, name)
    if name == "ReplicaSupervisor":
        from . import supervisor
        return getattr(supervisor, name)
    if name in ("ContinuousBatcher", "PagedKVCache",
                "RefcountingBlockAllocator"):
        from ..nlp import paged
        return getattr(paged, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
