"""paddle_tpu.serving.trace — per-request trace timelines and the step
flight recorder.

The serving stack's forensic layer: five mechanisms interact on the hot
path (prefix-cache sharing, bucketed/chunked prefill, fused mixed-batch
steps, multi-unit piggyback, the ragged attention kernel) and aggregate
metrics can't answer *where one request's time went* or *what the
scheduler decided on the step that failed*. This module can, and it is
cheap enough to leave on in production:

  * `TraceSink` — lock-safe, bounded collector of typed per-request
    events (enqueued, admitted, prepared, prefill_chunk, first_token,
    decode_emit, retired, finished/cancelled/failed/timed_out). The
    engine creates one and threads it into the batcher; every emission
    is a host-side dict append — no device syncs, no recompiles (the
    compiled-shape memo keys never see the sink). Timelines read back
    as structured dicts and export as Chrome-trace / Perfetto JSON
    (`to_chrome_trace()`: pid = the engine process, tid = the batch
    slot a request occupied, plus lanes for queued requests and engine
    step spans).
  * `FlightRecorder` — a bounded ring of one record per batcher step
    tick (mode chosen, unit composition, bucket / group pad, free
    slots / blocks, compile-memo hit or miss), recorded *before* the
    device call so the tick that raises is the last record in the ring.
    The engine's step-level exception boundary dumps the ring plus
    allocator / queue state to JSON on failure.

Timestamps come from `time.perf_counter` — the same clock
`MetricsRegistry.timer` measures with — so serving timelines line up
with the `serving.step_s` histogram and, when a jax profiler capture is
running, with the host `RecordEvent` spans on the XPlane timeline.

Dependency-free on purpose (no jax import, like `serving.cache`):
`nlp.paged` may construct a `FlightRecorder` without pulling the
serving engine, and `tools/trace_report.py` reads the exported JSON
with nothing but the standard library.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Union

__all__ = ["TraceSink", "FlightRecorder"]

# Chrome-trace lanes for events that are not anchored to a batch slot:
# requests still queued (no slot yet), the engine's per-step spans, and
# the DEVICE-wall spans a profiler capture window measures (kept on
# their own lane so host wall and device wall render side by side).
# Batch slots use tid = slot index (0..max_batch-1), far below these.
_DEVICE_TID = 9997
_QUEUE_TID = 9998
_STEPS_TID = 9999


class TraceSink:
    """Lock-safe, bounded, always-on-cheap collector of per-request
    trace timelines.

    One timeline per request: `start()` opens it (returning a string
    trace id the engine stamps on the request handle), `alias()` maps a
    batcher rid onto it so batcher-side emissions resolve to the same
    timeline, `emit()` appends typed events, and `finish()` appends the
    terminal event and moves the timeline onto a bounded ring of
    completed requests. Event kinds are free-form strings; the serving
    stack's vocabulary includes the fault-tolerance events `requeued`
    (a quarantine victim or rolled-back pending sibling going back to
    the queue front) and `retried` (a transient culprit parked for a
    backoff re-admission) next to the lifecycle kinds listed above. An int ref with no alias auto-opens a timeline
    keyed ``rid<n>`` so a standalone `ContinuousBatcher` can trace
    without an engine.

    Bounds: at most `max_events` events per timeline (overflow counted
    in `dropped_events`; the terminal event always lands), at most
    `max_requests` completed timelines retained, and at most
    `max_requests` LIVE timelines — when a producer that never calls
    `finish()` (a standalone batcher's auto-opened rid timelines)
    overflows that, the oldest live timeline is displaced onto the
    completed ring and its aliases drop, so memory stays bounded in
    every mode. Every emission is a host-side dict append under one
    lock — no device values may ever cross into an event (ptlint
    SYNC001 polices the emission helpers).
    """

    def __init__(self, max_requests: int = 256, max_events: int = 512,
                 max_live: Optional[int] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self._lock = threading.Lock()
        self._clock = clock
        self.origin = clock()
        self._seq = 0
        self._max_events = max_events
        # the live bound exists to cap finish()-less producers; a
        # producer that DOES finish timelines (the engine) must size it
        # above its maximum concurrent request count, or a deep queued
        # burst would displace still-running requests (losing their
        # terminals and splitting them across phantom rid timelines)
        self._max_live = max(1, int(max_requests if max_live is None
                                    else max_live))
        self._live: Dict[str, Dict[str, Any]] = {}
        self._done: deque = deque(maxlen=max_requests)
        self._alias: Dict[int, str] = {}
        # non-request lanes: engine step spans (bounded like the rest)
        self._spans: deque = deque(maxlen=4 * max_requests)
        # loss accounting: NOTHING vanishes silently — per-timeline
        # overflow, emissions on vanished/finished timelines, and live
        # displacements each tick a counter
        self.dropped_events = 0
        self.displaced_live = 0

    # ---- emission (hot path: host-side appends only) --------------------
    def now(self) -> float:
        """The sink's clock (default `time.perf_counter` — the same
        timebase as `MetricsRegistry.timer`)."""
        return self._clock()

    def start(self, label: Optional[str] = None, **attrs) -> str:
        """Open a new timeline; returns its trace id (``t<n>``)."""
        with self._lock:
            tid = f"t{self._seq}"
            self._seq += 1
            self._live[tid] = {"trace_id": tid, "label": label,
                               "slot": None, "done": False, "events": []}
            if attrs:
                self._append_locked(self._live[tid], "start", None,
                                    self._clock(), attrs, forced=True)
            self._bound_live_locked()
            return tid

    def alias(self, rid: int, trace_id: str) -> None:
        """Map a batcher request id onto an open timeline, so
        batcher-side `emit(rid, ...)` calls resolve to it."""
        with self._lock:
            self._alias[int(rid)] = trace_id

    def emit(self, ref: Union[int, str], kind: str,
             dur: Optional[float] = None, **attrs) -> None:
        """Append one typed event to `ref`'s timeline. `ref` is a trace
        id, or a batcher rid (resolved through `alias`, auto-opening a
        ``rid<n>`` timeline when unaliased). `dur` (seconds) marks a
        span; attrs must be JSON-safe host values."""
        t = self._clock()
        with self._lock:
            tl = self._resolve_locked(ref)
            if tl is None or tl["done"]:
                # vanished (displaced) or already-terminal timeline:
                # the event is lost, but never silently
                self.dropped_events += 1
                return
            self._append_locked(tl, kind, dur, t, attrs)

    def finish(self, ref: Union[int, str], kind: str, **attrs) -> None:
        """Append the terminal event (always lands, bounds or not) and
        retire the timeline onto the completed ring. Idempotent: a
        second finish on the same timeline is a no-op."""
        t = self._clock()
        with self._lock:
            tl = self._resolve_locked(ref)
            if tl is None or tl["done"]:
                return
            self._append_locked(tl, kind, None, t, attrs, forced=True)
            tl["done"] = True
            self._live.pop(tl["trace_id"], None)
            self._done.append(tl)
            for rid in [r for r, k in self._alias.items()
                        if k == tl["trace_id"]]:
                del self._alias[rid]

    def span(self, name: str, dur: float, lane: str = "steps",
             **attrs) -> None:
        """Record one engine-level span (e.g. ``engine.step``) ending
        now and lasting `dur` seconds — the sink-side twin of a
        `MetricsRegistry.timer` observation. `lane` picks the Chrome
        lane: "steps" (default) or "device" (the device-wall spans a
        profiler capture window measures, rendered next to the host
        step spans so the two walls are visually comparable)."""
        t1 = self._clock()
        with self._lock:
            self._spans.append({"kind": name, "t": t1 - dur, "dur": dur,
                                "lane": lane, "attrs": dict(attrs)})

    # ---- internal -------------------------------------------------------
    def _resolve_locked(self, ref):
        if isinstance(ref, int):
            key = self._alias.get(ref)
            if key is None:
                key = f"rid{ref}"
                if key not in self._live and not any(
                        tl["trace_id"] == key for tl in self._done):
                    self._live[key] = {"trace_id": key, "label": None,
                                       "slot": None, "done": False,
                                       "events": []}
                    self._bound_live_locked()
            return self._live.get(key)
        return self._live.get(ref)

    def _bound_live_locked(self):
        """Keep the live set bounded even for producers that never
        finish() (standalone-batcher rid timelines): displace the
        oldest live timeline onto the completed ring and drop its
        aliases. Insertion order IS age — dicts preserve it."""
        while len(self._live) > self._max_live:
            key, tl = next(iter(self._live.items()))
            del self._live[key]
            self._done.append(tl)
            self.displaced_live += 1
            for rid in [r for r, k in self._alias.items() if k == key]:
                del self._alias[rid]

    def _append_locked(self, tl, kind, dur, t, attrs, forced=False):
        if not forced and len(tl["events"]) >= self._max_events:
            self.dropped_events += 1
            return
        ev: Dict[str, Any] = {"kind": kind, "t": t}
        if dur is not None:
            ev["dur"] = dur
        if attrs:
            ev["attrs"] = dict(attrs)
            slot = attrs.get("slot")
            if slot is not None:
                tl["slot"] = slot
        tl["events"].append(ev)

    # ---- read side ------------------------------------------------------
    def timeline(self, ref: Union[int, str]) -> Optional[Dict[str, Any]]:
        """One request's timeline as a structured dict (deep copy), or
        None when `ref` names no live or retained timeline."""
        with self._lock:
            if isinstance(ref, int):
                ref = self._alias.get(ref, f"rid{ref}")
            tl = self._live.get(ref)
            if tl is None:
                tl = next((d for d in self._done
                           if d["trace_id"] == ref), None)
            return None if tl is None else self._copy(tl)

    def timelines(self) -> List[Dict[str, Any]]:
        """Every retained timeline (completed ring first, then live),
        as structured dicts."""
        with self._lock:
            return [self._copy(tl) for tl in list(self._done)
                    + list(self._live.values())]

    @staticmethod
    def _copy(tl):
        out = dict(tl)
        out["events"] = [
            {**ev, "attrs": dict(ev["attrs"])} if "attrs" in ev
            else dict(ev) for ev in tl["events"]]
        return out

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Export every retained timeline as Chrome-trace / Perfetto
        JSON (the ``traceEvents`` array format): pid 1 is the engine,
        tid is the batch slot a request occupied at that point (queued
        events ride a ``queue`` lane, engine step spans a ``steps``
        lane). Events with a duration render as complete ("X") spans,
        the rest as thread-scoped instants ("i"); timestamps are
        microseconds from the sink's origin, monotonic by
        construction."""
        pid = 1
        with self._lock:
            tls = [self._copy(tl) for tl in list(self._done)
                   + list(self._live.values())]
            spans = [dict(s) for s in self._spans]
            origin = self.origin
        events: List[Dict[str, Any]] = []
        tids = set()

        def us(t):
            # clamped: a span whose start predates the sink's origin
            # (possible only for hand-fed durations) must not produce
            # a negative timestamp Perfetto rejects
            return max(0.0, (t - origin) * 1e6)

        for tl in tls:
            cur_tid = _QUEUE_TID
            for ev in tl["events"]:
                attrs = ev.get("attrs", {})
                slot = attrs.get("slot")
                if slot is not None:
                    cur_tid = int(slot)
                tids.add(cur_tid)
                out = {"name": ev["kind"], "pid": pid, "tid": cur_tid,
                       "args": {"trace_id": tl["trace_id"], **attrs}}
                if "dur" in ev:
                    # emission stamps the span's END (the event is
                    # recorded after the measured call returns) — the
                    # rendered span starts dur earlier, so it nests
                    # inside the engine.step span that contained it
                    out["ph"] = "X"
                    out["ts"] = us(ev["t"] - ev["dur"])
                    out["dur"] = ev["dur"] * 1e6
                else:
                    out["ph"] = "i"
                    out["ts"] = us(ev["t"])
                    out["s"] = "t"
                events.append(out)
        for s in spans:
            tid = (_DEVICE_TID if s.get("lane") == "device"
                   else _STEPS_TID)
            tids.add(tid)
            events.append({"name": s["kind"], "ph": "X", "pid": pid,
                           "tid": tid, "ts": us(s["t"]),
                           "dur": s["dur"] * 1e6,
                           "args": dict(s["attrs"])})
        events.sort(key=lambda e: e["ts"])
        meta = [{"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": "paddle_tpu.serving engine"}}]
        for tid in sorted(tids):
            name = ("queue" if tid == _QUEUE_TID
                    else "engine steps" if tid == _STEPS_TID
                    else "device steps" if tid == _DEVICE_TID
                    else f"slot {tid}")
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": name}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


class FlightRecorder:
    """Bounded ring buffer of per-step scheduler records — the serving
    stack's black box.

    `ContinuousBatcher` appends one record per device-step tick
    *before* dispatching the call (mode chosen, unit composition,
    bucket / group pad, free slots / blocks, compile-memo hit or
    miss), so when a step raises, the failing tick is the last record
    in the ring. `ServingEngine.dump_flight_recorder()` (and the
    engine's step-failure boundary) serialize `records()` plus
    allocator / queue state to JSON. Records are plain JSON-safe
    dicts; appends are host-side only and lock-safe."""

    def __init__(self, cap: int = 64,
                 clock: Callable[[], float] = time.perf_counter):
        self._lock = threading.Lock()
        self._clock = clock
        self._ring: deque = deque(maxlen=max(1, int(cap)))
        self._seq = 0

    @property
    def cap(self) -> int:
        """Ring capacity: the last `cap` step records are retained."""
        with self._lock:
            return self._ring.maxlen

    @property
    def seq(self) -> int:
        """Records ever written (not just retained). The engine's
        quarantine compares this against the value it saw after the
        last successful step: an exception with an UNCHANGED seq came
        from before any tick was recorded (an admission-time failure),
        so the ring's last record would be a stale tick — no basis for
        convicting anyone."""
        with self._lock:
            return self._seq

    def record(self, mode: str, **fields) -> None:
        """Append one step record: `mode` is the scheduler's decision
        for the tick ("decode" | "fused" | "prefill"), `fields` carry
        the tick's composition and pool state (JSON-safe host values
        only)."""
        with self._lock:
            self._ring.append({"seq": self._seq, "t": self._clock(),
                               "mode": mode, **fields})
            self._seq += 1

    def records(self) -> List[Dict[str, Any]]:
        """The retained records, oldest first (copies — safe to
        serialize while the engine keeps stepping)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
