"""paddle_tpu.serving.supervisor — replica lifecycle state machine for
the self-healing serving tier.

PR 8 gave one engine a hung-step watchdog and PR 11 gave the Router
cross-replica failover — but a replica the watchdog flips UNHEALTHY
stayed dead weight until a human restarted the process. The
`ReplicaSupervisor` closes that loop: detect → kill → respawn →
re-warm → rejoin.

Per router slot the supervisor runs a three-state machine:

    SERVING ──(engine UNHEALTHY: watchdog trip or the PR 8
        │      consecutive-failure fuse)──▶ RESTARTING
        │                                      │ teardown the dead
        │                                      │ engine (bounded,
        │                                      │ drain=False), then per
        │                                      │ attempt: rebuild from
        │                                      │ the router's retained
        │                                      │ params/cfg/overrides
        │                                      │ (same replica_id) →
        │                                      │ AOT warmup() → start()
        │                                      │ → synthetic probe
        │                                      │ generation — the
        │                                      │ READINESS GATE: the
        │                                      │ slot re-enters
        │                                      │ `Router._views` only
        │                                      │ after the probe lands
        │◀──(probe passed: swap + affinity ────┘
        │    invalidate + SERVING)
        │      failed attempts back off exponentially with jitter;
        ▼      `breaker_threshold` failures inside `breaker_window_s` …
    FAILED — crash-loop circuit breaker OPEN: the slot is pinned out
        of rotation (surfaced in health()/`/health`/Prometheus) so
        operators see a permanently lost replica instead of silent
        flapping. Terminal until the process restarts.

The readiness gate exists for two reasons: a respawned engine with a
cold compile cache would serve TTFT cliffs (warmup() re-compiles the
whole ladder off-rotation), and a half-alive replica (constructed but
wedged on its first device call — the persistent-hang shape) must
never take traffic; the probe generation proves the whole
admission→prefill→decode→channel path end to end before the policy
may pick the slot again.

Affinity hygiene: the respawned engine's KV pool is empty, so every
router-level affinity entry pointing at the slot is invalidated at
swap time — last-writer-wins re-pointing must not keep steering
prefix siblings to a cold replica; the index re-learns from the
traffic the policy routes there afterwards.

KV preservation (the PR 12 "slot-in-place KV-pool preservation" gap):
before tearing the old engine down, the cycle drains-and-exports its
active requests' KV (`engine.drain_export` →
`serving.kvtransfer.KVSnapshot` pairs) and, once the fresh engine
passes the readiness gate, resumes each one warm via `submit_import`
— zero re-prefilled tokens across the restart. A wedged engine that
cannot drain yields no pairs (its requests ride the normal cold
failover); a drained request the cycle cannot resume (breaker trip,
stop, import failure) FAILS with reason "respawn_failed" and its
snapshot attached, so the router's failover re-places it warm on a
surviving replica. `restart_slot(i)` exposes the same cycle as a
planned restart (rolling maintenance without losing in-flight work).

Lock discipline (LOCK001): the supervisor thread acquires
`Router._lock` only for the state flips and the engine swap — never
while tearing down, constructing, warming or probing an engine (all
blocking work runs lock-free; the global order `Router._lock →
ServingEngine._lock → AdmissionQueue._lock` is preserved because the
swap itself calls no engine method under the router lock).

Concurrency: the poll thread only DETECTS; each recovery cycle runs
on its own per-slot thread, so one slot crash-looping through its
backoff ladder never delays detection or recovery of another slot.

Deterministic by construction: backoff jitter comes from a seeded
`random.Random` (draws serialized across slot threads), so a
single-slot chaos test replays the same schedule.
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .request import RequestState

__all__ = ["ReplicaSupervisor", "SLOT_SERVING", "SLOT_RESTARTING",
           "SLOT_FAILED", "compute_backoff"]

# Slot lifecycle states (strings on purpose: they travel through
# health() JSON to /health and the bench unchanged).
SLOT_SERVING = "SERVING"
"""Slot state: the replica is in rotation and the policy may pick it."""
SLOT_RESTARTING = "RESTARTING"
"""Slot state: the dead engine is being torn down / respawned / warmed
behind the readiness gate — out of rotation, recovery underway."""
SLOT_FAILED = "FAILED"
"""Slot state: the crash-loop circuit breaker opened — the slot is
pinned out of rotation until the process restarts (operator action)."""


def compute_backoff(attempt: int, *, base_s: float, max_s: float,
                    jitter: float, rng: random.Random) -> float:
    """Exponential backoff with jitter for respawn attempt `attempt`
    (1-based): ``min(max_s, base_s * 2**(attempt-1))`` scaled by a
    uniform ``[1, 1+jitter)`` factor drawn from `rng` — seeded, so a
    chaos run replays the same schedule."""
    if attempt < 1:
        return 0.0
    # exponent clamped BEFORE exponentiation: a long-lived crash loop
    # must saturate at max_s, not OverflowError the restart thread
    raw = min(float(max_s),
              float(base_s) * (2.0 ** min(attempt - 1, 63)))
    return raw * (1.0 + float(jitter) * rng.random())


class _Slot:
    """One replica slot's lifecycle record (supervisor-thread owned;
    `state` is read lock-free by the router's routing path — a plain
    attribute store, atomic under the GIL)."""

    __slots__ = ("index", "state", "restarts", "restart_failures",
                 "failure_times", "backoff_s", "circuit_open",
                 "warm_compile_count", "last_error", "restarting_since",
                 "via_reset")

    def __init__(self, index: int):
        self.index = index
        self.state = SLOT_SERVING
        self.restarts = 0
        self.restart_failures = 0
        self.failure_times: deque = deque()
        self.backoff_s = 0.0
        self.circuit_open = False
        self.warm_compile_count: Optional[int] = None
        self.last_error: Optional[str] = None
        self.restarting_since: Optional[float] = None
        # this recovery cycle was initiated by an operator breaker
        # reset (stamped on the fresh engine's `restarted` span — the
        # dead engine's sink, where `breaker_reset` lands, is dropped
        # at swap, so provenance must ride the surviving sink)
        self.via_reset = False

    def info(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "restarts": self.restarts,
            "restart_failures": self.restart_failures,
            "circuit_open": self.circuit_open,
            "backoff_s": self.backoff_s,
            "warm_compile_count": self.warm_compile_count,
            "last_error": self.last_error,
            "restarting": self.state == SLOT_RESTARTING,
            "restarting_since": self.restarting_since,
        }


class ReplicaSupervisor:
    """Auto-restart supervisor over a `Router`'s replica slots.

    Constructed (and started) by `Router(auto_restart=True, ...)` —
    the router must have built its replicas itself (it retains the
    params/cfg/per-replica overrides a respawn rebuilds from). Knobs
    arrive via `Router(restart_opts={...})`:

      * ``poll_s`` — health-poll cadence (default 0.05);
      * ``backoff_s`` / ``backoff_max_s`` / ``jitter`` — the
        exponential-backoff schedule between failed respawn attempts
        (defaults 0.25 / 8.0 / 0.25; jitter is seeded — see `seed`);
      * ``breaker_threshold`` / ``breaker_window_s`` — the crash-loop
        circuit breaker: this many CONSECUTIVE failed respawns in one
        recovery cycle — or this many inside the trailing window
        across cycles (flap detection) — pins the slot FAILED
        (defaults 3 / 60.0);
      * ``probe_prompt`` / ``probe_new_tokens`` / ``probe_timeout_s``
        — the readiness probe: a synthetic generation the respawned
        engine must complete (after AOT warmup) before the slot
        rejoins rotation (defaults ``[1, 2, 3]`` / 2 / 120.0);
      * ``probe_mirror`` — shadow-traffic readiness: replay the shape
        of a recently-served LIVE request (prompt + budget, captured
        from the dead engine before teardown) instead of the synthetic
        probe prompt, so the gate exercises the compiled buckets real
        traffic actually hits; falls back to the synthetic prompt when
        the dead engine served nothing or cannot be read
        (default False);
      * ``teardown_timeout_s`` — bound on each dead-engine
        ``shutdown(drain=False)`` (default 2.0);
      * ``seed`` — jitter RNG seed (default 0).

    `info()` is the per-slot operator surface `Router.health()` and
    `snapshot()` embed; `slot_serving(i)` is the lock-free gate
    `Router._views` consults before offering slot `i` to the policy.
    """

    def __init__(self, router, *, poll_s: float = 0.05,
                 backoff_s: float = 0.25, backoff_max_s: float = 8.0,
                 jitter: float = 0.25, breaker_threshold: int = 3,
                 breaker_window_s: float = 60.0,
                 probe_prompt: Optional[Sequence[int]] = None,
                 probe_new_tokens: int = 2,
                 probe_timeout_s: float = 120.0,
                 probe_mirror: bool = False,
                 teardown_timeout_s: float = 2.0,
                 seed: int = 0, clock=time.monotonic):
        self._router = router
        self._poll_s = float(poll_s)
        self._backoff_base = float(backoff_s)
        self._backoff_max = float(backoff_max_s)
        self._jitter = float(jitter)
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_window_s = float(breaker_window_s)
        self._probe_prompt = list(probe_prompt) if probe_prompt \
            else [1, 2, 3]
        self._probe_new = int(probe_new_tokens)
        self._probe_timeout_s = float(probe_timeout_s)
        self._probe_mirror = bool(probe_mirror)
        self._teardown_timeout_s = float(teardown_timeout_s)
        self._rng = random.Random(seed)
        # restart cycles run CONCURRENTLY (one thread per slot) and
        # share the jitter rng — serialize just the draw
        self._rng_lock = threading.Lock()
        self._clock = clock
        self._slots: List[_Slot] = [
            _Slot(i) for i in range(len(router.engines))]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._restart_threads: Dict[int, threading.Thread] = {}

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        """Launch the supervisor thread (idempotent)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="paddle-tpu-supervisor",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 10.0) -> bool:
        """Stop the supervisor; joins the poll thread AND every
        in-flight per-slot restart thread, bounded. An in-flight
        restart notices the stop flag at its next wait/poll, tears
        down any engine it built but never swapped in WITHOUT charging
        the slot a respawn failure (a clean shutdown must not pollute
        the crash-loop accounting), and exits — so shutdown during a
        restart joins bounded instead of leaking a replica."""
        self._stop.set()
        clean = True
        if self._thread is not None:
            self._thread.join(timeout)
            clean = not self._thread.is_alive()
        for t in list(self._restart_threads.values()):
            t.join(timeout)
            if t.is_alive():
                clean = False
        return clean

    # ---- router-facing views --------------------------------------------
    def slot_serving(self, i: int) -> bool:
        """True when slot `i` is in rotation (lock-free read — the
        routing path calls this per candidate per request)."""
        return self._slots[i].state == SLOT_SERVING

    def info(self) -> Dict[str, Dict[str, Any]]:
        """Per-slot lifecycle detail keyed by replica id — the
        operator surface embedded in `Router.health()`/`snapshot()`."""
        return {self._router.engines[s.index].replica_id: s.info()
                for s in self._slots}

    def states(self) -> List[str]:
        """Slot states by index (SERVING / RESTARTING / FAILED)."""
        return [s.state for s in self._slots]

    def reset_breaker(self, index: int) -> bool:
        """Operator override for a breaker-pinned slot: clear slot
        `index`'s crash-loop history (failure window, circuit flag,
        consecutive count) and re-enter the normal recovery cycle —
        RESTARTING, then the usual rebuild → warmup → probe readiness
        gate on a fresh per-slot thread, so a revived slot still
        cannot take traffic before proving it can serve (and a slot
        whose underlying fault persists trips the breaker again
        instead of flapping). Returns False when the slot is not
        FAILED (SERVING or mid-RESTARTING — nothing to reset);
        `Router.reset_breaker` / `POST /admin/reset_breaker` are the
        operator surfaces over this."""
        slot = self._slots[int(index)]
        with self._router._lock:
            if slot.state != SLOT_FAILED or self._stop.is_set():
                return False
            slot.state = SLOT_RESTARTING
            slot.circuit_open = False
            slot.failure_times.clear()
            slot.last_error = None
            slot.restarting_since = self._clock()
            slot.via_reset = True
        # the engine still in the slot is the dead incarnation the
        # breaker pinned — _restart_slot re-tears it down (idempotent)
        # before rebuilding, exactly like a detection-driven cycle
        dead = self._router.engines[slot.index]
        t = threading.Thread(
            target=self._restart_slot, args=(slot, dead),
            name=f"paddle-tpu-restart-{slot.index}", daemon=True)
        self._restart_threads[slot.index] = t
        t.start()
        return True

    def restart_slot(self, index: int) -> bool:
        """Planned restart of a SERVING slot (rolling maintenance):
        flips it RESTARTING and runs the normal recovery cycle on a
        per-slot thread — but because the engine is still healthy, the
        drain-export step actually succeeds, so its in-flight requests
        resume WARM on the respawned engine (zero re-prefilled
        tokens). Returns False when the slot is not SERVING (already
        restarting, breaker-pinned — use `reset_breaker` — or the
        supervisor is stopping)."""
        slot = self._slots[int(index)]
        with self._router._lock:
            if slot.state != SLOT_SERVING or self._stop.is_set():
                return False
            slot.state = SLOT_RESTARTING
            slot.restarting_since = self._clock()
            slot.last_error = None
        eng = self._router.engines[slot.index]
        t = threading.Thread(
            target=self._restart_slot, args=(slot, eng),
            name=f"paddle-tpu-restart-{slot.index}", daemon=True)
        self._restart_threads[slot.index] = t
        t.start()
        return True

    # ---- the supervisor threads -----------------------------------------
    def _loop(self) -> None:
        """The health-poll thread: detection only. Each detected death
        flips its slot RESTARTING (so detection can never double-fire)
        and hands the recovery cycle to a dedicated per-slot thread —
        one slot's long respawn ladder (teardown + warmup + probe +
        backoff, potentially minutes in a crash loop) must never block
        detection or recovery of the OTHER slots."""
        while not self._stop.wait(self._poll_s):
            for slot in self._slots:
                if self._stop.is_set():
                    return
                if slot.state != SLOT_SERVING:
                    continue
                eng = self._router.engines[slot.index]
                if eng.health()["status"] == "UNHEALTHY":
                    with self._router._lock:
                        slot.state = SLOT_RESTARTING
                        slot.restarting_since = self._clock()
                        slot.last_error = None
                    t = threading.Thread(
                        target=self._restart_slot, args=(slot, eng),
                        name=f"paddle-tpu-restart-{slot.index}",
                        daemon=True)
                    self._restart_threads[slot.index] = t
                    t.start()

    def _restart_slot(self, slot: _Slot, dead) -> None:
        """One detect→kill→respawn→re-warm→rejoin cycle for `slot`
        (its own thread; the slot is already RESTARTING). Ends with
        the slot SERVING (fresh engine swapped in, affinity
        invalidated) or FAILED (breaker open), or mid-RESTARTING if
        the supervisor was stopped."""
        r = self._router
        t0 = self._clock()
        if dead.trace is not None:
            # forensics on the dead engine's sink: if the breaker ends
            # up pinning the slot FAILED this sink is what the merged
            # trace still exports
            dead.trace.span("restarting", dur=0.0,
                            replica=dead.replica_id)
        # drain-and-export BEFORE teardown: active requests surrender
        # their KV so the respawned slot resumes them without
        # re-prefill. A wedged engine thread cannot drain —
        # drain_export times out to [] and those requests ride the
        # normal cold failover instead.
        # shadow-traffic mirror: grab the newest live request shape
        # BEFORE teardown wipes the dead engine (best-effort — a
        # wedged engine, or one that served nothing, falls back to
        # the synthetic probe prompt)
        mirror: Optional[Tuple[List[int], int]] = None
        if self._probe_mirror:
            try:
                served = dead.recent_prompts()
                if served:
                    mirror = served[-1]
            # ptlint: disable=EXC001 — mirror capture is best-effort:
            # a dying engine that cannot report its traffic must still
            # be respawned; the synthetic probe covers the gate
            except Exception:
                mirror = None
        pairs: List[Tuple[Any, Any]] = []
        try:
            pairs = dead.drain_export(timeout=self._teardown_timeout_s)
        # ptlint: disable=EXC001 — export is best-effort: a dying
        # engine that cannot even drain must still be torn down and
        # respawned; its requests fail over cold
        except Exception:
            pairs = []
        self._teardown(dead)
        attempt = 0
        while not self._stop.is_set():
            fresh = None
            try:
                fresh = r._build_replica(slot.index)
                fresh.warmup()
                fresh.start()
                self._probe(fresh, mirror=mirror)
            # ptlint: disable=EXC001 — respawn attempt boundary: ANY
            # failure (constructor, warmup, probe, watchdog trip) is a
            # failed attempt feeding the backoff/breaker machinery —
            # letting it escape would kill the supervisor thread and
            # silently end self-healing for every slot
            except Exception as e:
                if fresh is not None:
                    self._teardown(fresh)
                if self._stop.is_set():
                    # a stop interrupted the attempt (probe bailed,
                    # warmup raced shutdown): clean shutdown is NOT a
                    # respawn failure — charging it would pollute the
                    # crash-loop accounting and could even pin the
                    # slot FAILED in the final scraped snapshot
                    self._fail_exported(pairs)
                    return
                slot.restart_failures += 1
                slot.failure_times.append(self._clock())
                slot.last_error = repr(e)
                r._c_restart_failures.inc()
                if self._breaker_tripped(slot, consecutive=attempt + 1):
                    with r._lock:
                        slot.state = SLOT_FAILED
                        slot.circuit_open = True
                        slot.backoff_s = 0.0
                    r._c_circuit_open.inc()
                    r._g_restart_backoff[slot.index].set(0.0)
                    self._fail_exported(pairs)
                    return
                attempt += 1
                with self._rng_lock:     # concurrent slots share rng
                    backoff = compute_backoff(
                        attempt, base_s=self._backoff_base,
                        max_s=self._backoff_max, jitter=self._jitter,
                        rng=self._rng)
                slot.backoff_s = backoff
                r._g_restart_backoff[slot.index].set(backoff)
                self._stop.wait(backoff)
                continue
            # readiness gate passed: rejoin rotation. The compile count
            # recorded here is the zero-post-warmup baseline for the
            # respawned engine (the bench's recompile gate reads it).
            warm = fresh.batcher.compile_count
            with r._lock:
                r.engines[slot.index] = fresh
                invalidated = r._affinity.invalidate(slot.index)
                slot.state = SLOT_SERVING
                slot.restarts += 1
                slot.warm_compile_count = warm
                slot.backoff_s = 0.0
                slot.restarting_since = None
            r._c_restarts.inc()
            r._g_restart_backoff[slot.index].set(0.0)
            # warm resume: the drained requests re-enter decode on the
            # fresh engine via KV import — zero re-prefilled tokens
            # across the restart. Their router entries still point at
            # this slot index, so the bridge keeps streaming into the
            # same outer handles.
            resumed = 0
            for snap, req in pairs:
                if req.done or req.cancel_requested:
                    continue
                try:
                    fresh.submit_import(snap, req)
                    resumed += 1
                # ptlint: disable=EXC001 — per-request resume boundary:
                # one unresumable snapshot must not strand the rest;
                # the failed request rides failover with its KV attached
                except Exception as e:
                    req.kv_snapshot = snap
                    req._finish(RequestState.FAILED, "respawn_failed",
                                error=e, now=self._clock())
            if fresh.trace is not None:
                fresh.trace.span(
                    "restarted", dur=self._clock() - t0,
                    replica=fresh.replica_id, attempts=attempt + 1,
                    affinity_invalidated=invalidated,
                    resumed_from_snapshot=resumed,
                    via_breaker_reset=slot.via_reset)
            slot.via_reset = False
            return
        # stopped mid-restart: the slot stays RESTARTING; the dead
        # engine still in the slot was already torn down and
        # Router.shutdown re-tears it idempotently — but the drained
        # requests must not hang on a box nobody will resume
        self._fail_exported(pairs)

    def _probe(self, eng,
               mirror: Optional[Tuple[List[int], int]] = None) -> None:
        """The readiness probe: one generation through the full
        admission→prefill→decode→channel path — the `mirror` shape (a
        recently-served live prompt + budget, when ``probe_mirror``
        captured one) or the synthetic probe prompt. Polls in short
        slices so a supervisor stop interrupts it bounded; raises on
        timeout, stop, an empty generation, or a respawned engine that
        is not HEALTHY afterwards (its own watchdog tripping during
        the probe lands here — the persistent-hang shape)."""
        if mirror is not None:
            prompt, max_new = list(mirror[0]), int(mirror[1])
        else:
            prompt, max_new = self._probe_prompt, self._probe_new
        req = eng.submit(prompt, max_new_tokens=max_new)
        deadline = self._clock() + self._probe_timeout_s
        while True:
            if self._stop.is_set():
                eng.cancel(req)
                raise RuntimeError("supervisor stopped mid-probe")
            try:
                out = req.result(timeout=0.05)
                break
            except TimeoutError:
                if self._clock() > deadline:
                    eng.cancel(req)
                    raise RuntimeError(
                        f"readiness probe timed out after "
                        f"{self._probe_timeout_s}s")
        if not out:
            raise RuntimeError("readiness probe generated no tokens")
        h = eng.health()
        if h["status"] != "HEALTHY" or not h.get("ready", True):
            raise RuntimeError(
                f"respawned replica not ready after probe: "
                f"{h['status']}")

    def _teardown(self, eng) -> None:
        """Bounded, best-effort engine teardown: `shutdown(drain=False)`
        joins bounded even when the engine thread is wedged inside a
        device call (the watchdog's 1s-join path)."""
        try:
            eng.shutdown(drain=False, timeout=self._teardown_timeout_s)
        # ptlint: disable=EXC001 — teardown boundary: a dead replica
        # failing to die cleanly must not kill the supervisor (the
        # engine thread is a daemon; the process reclaims it)
        except Exception:
            pass

    def _fail_exported(self, pairs: List[Tuple[Any, Any]]) -> None:
        """Fail every drained-but-never-resumed request with its
        snapshot ATTACHED: "respawn_failed" is in the router's default
        failover predicate, so each one re-places warm (KV import) on
        a surviving replica instead of hanging on a box this cycle
        will never service."""
        for snap, req in pairs:
            if req.done:
                continue
            req.kv_snapshot = snap
            req._finish(RequestState.FAILED, "respawn_failed",
                        now=self._clock())

    def _breaker_tripped(self, slot: _Slot, consecutive: int) -> bool:
        """Crash-loop circuit breaker: True when `breaker_threshold`
        CONSECUTIVE failures landed in the current recovery cycle
        (`consecutive` — immune to attempts that each outlast the
        window: a 120s probe timeout must not outrun a 60s window and
        crash-loop forever), or when that many failures landed inside
        the trailing `breaker_window_s` across cycles (flap detection:
        a slot that rejoins and promptly dies again)."""
        if consecutive >= self._breaker_threshold:
            return True
        now = self._clock()
        while slot.failure_times and \
                now - slot.failure_times[0] > self._breaker_window_s:
            slot.failure_times.popleft()
        return len(slot.failure_times) >= self._breaker_threshold
