"""paddle_tpu.serving.metrics — lock-safe serving metrics.

Reference analog: PaddleNLP serving / FastDeploy expose Prometheus-style
counters (requests accepted/rejected, TTFT, inter-token latency, queue
depth, cache usage). Here the registry is in-process: counters, gauges
and histograms behind one lock, with a plain-dict `snapshot()` so tests,
benchmarks and an eventual HTTP frontend (ROADMAP open item) read one
consistent view without scraping.

Profiler integration: `MetricsRegistry.timer(name)` is a context manager
that both observes wall time into a histogram AND opens a
`paddle_tpu.profiler.RecordEvent` span, so engine phases (admission,
decode step) land in the same XPlane trace as the device work they
schedule.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry"]

# Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]* — everything else
# (the registry's dotted names like "serving.step_s") maps to "_"
_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


class Counter:
    """Monotonic counter (requests_admitted, tokens_generated, ...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._value = 0
        self._lock = lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue_depth, kv_blocks_in_use, ...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, v: float) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


# Default cumulative-bucket ladder for latency histograms exported as
# native Prometheus histograms (seconds; +Inf is implicit) — wide
# enough for TTFT under compile-cliff conditions, fine enough for
# inter-token gaps.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    """Latency distribution (TTFT, queue wait, per-step decode time).

    Keeps a bounded ring of raw observations (default 2048): count/sum
    are exact over the histogram's lifetime, percentiles are over the
    most recent window — the steady-state view a serving dashboard
    wants, without unbounded memory on long-lived engines.

    `buckets` (optional, ascending upper bounds; +Inf implicit) adds
    EXACT lifetime cumulative bucket counts next to the ring — the
    data a native Prometheus histogram family exports so an external
    Prometheus can compute its own burn rates instead of trusting the
    in-process windowed quantiles."""

    __slots__ = ("name", "_lock", "_ring", "_cap", "_count", "_sum",
                 "_min", "_max", "_bounds", "_bucket_counts")

    def __init__(self, name: str, lock: threading.RLock, cap: int = 2048,
                 buckets: Optional[List[float]] = None):
        self.name = name
        self._lock = lock
        self._ring: List[float] = []
        self._cap = cap
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._bounds: Optional[List[float]] = \
            None if buckets is None else sorted(float(b) for b in buckets)
        self._bucket_counts: Optional[List[int]] = \
            None if buckets is None else [0] * len(self._bounds)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            if len(self._ring) < self._cap:
                self._ring.append(v)
            else:
                self._ring[self._count % self._cap] = v
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if self._bounds is not None:
                # non-cumulative per-bucket counts here; buckets()
                # renders the cumulative le= view Prometheus expects
                for i, b in enumerate(self._bounds):
                    if v <= b:
                        self._bucket_counts[i] += 1
                        break

    def buckets(self) -> Optional[List[Tuple[float, int]]]:
        """Lifetime-exact CUMULATIVE (le, count) pairs (the +Inf bucket
        is the lifetime count and is implicit), or None when this
        histogram was created without a bucket ladder."""
        with self._lock:
            if self._bounds is None:
                return None
            out, acc = [], 0
            for b, c in zip(self._bounds, self._bucket_counts):
                acc += c
                out.append((b, acc))
            return out

    @staticmethod
    def _percentile(sorted_vals: List[float], q: float) -> float:
        # nearest-rank on the sorted window
        idx = min(len(sorted_vals) - 1,
                  max(0, int(round(q * (len(sorted_vals) - 1)))))
        return sorted_vals[idx]

    def percentile(self, q: float, since: int = 0) -> Optional[float]:
        """Nearest-rank percentile (q in [0, 1]) over the recent
        observation window, or None before the first observation —
        benchmark emitters read arbitrary quantiles (itl_ms_p99 & co)
        without re-implementing the windowing. `since` drops the first
        `since` lifetime observations (as counted by summary()["count"])
        from the window first, so a bench can rank only the samples
        recorded inside its timed region (e.g. skip the warmup request's
        compile-tainted inter-token gaps); observations that already
        fell off the ring are skipped implicitly."""
        with self._lock:
            if not self._ring:
                return None
            vals = self._ring
            if since > 0:
                if self._count <= self._cap:
                    ordered = vals
                else:
                    start = self._count % self._cap
                    ordered = vals[start:] + vals[:start]
                vals = ordered[max(0, since - (self._count - len(ordered))):]
                if not vals:
                    return None
            return self._percentile(sorted(vals), q)

    def summary(self) -> Dict[str, float]:
        """Lifetime and windowed statistics, under EXPLICIT keys so a
        long-lived engine's dashboard can't misread them: `count` /
        `sum` / `mean` / `min` / `max` are exact over the histogram's
        LIFETIME, while the percentiles AND `window_count` /
        `window_min` / `window_max` describe only the most recent
        `cap` observations still in the ring. Before the ring wraps
        the two views coincide; after it wraps, lifetime min/max may
        lie far outside the window the percentiles rank — which is
        why the windowed extrema get their own keys instead of being
        silently mixed in."""
        with self._lock:
            if not self._count:
                return {"count": 0}
            vals = sorted(self._ring)
            return {
                "count": self._count,
                "sum": self._sum,
                "mean": self._sum / self._count,
                "min": self._min,
                "max": self._max,
                "window_count": len(vals),
                "window_min": vals[0],
                "window_max": vals[-1],
                "p50": self._percentile(vals, 0.50),
                "p90": self._percentile(vals, 0.90),
                "p95": self._percentile(vals, 0.95),
                "p99": self._percentile(vals, 0.99),
            }


class _Timer:
    """Wall-time span → histogram observation + profiler RecordEvent.
    The measured interval stays readable on `.elapsed` after exit so
    derived metrics share the one measurement."""

    __slots__ = ("_hist", "_span", "_t0", "elapsed")

    def __init__(self, hist: Histogram, span):
        self._hist = hist
        self._span = span
        self._t0 = None
        self.elapsed: Optional[float] = None

    def __enter__(self):
        if self._span is not None:
            self._span.begin()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        if self._span is not None:
            self._span.end()
        self._hist.observe(self.elapsed)
        return False


class MetricsRegistry:
    """Named counters/gauges/histograms behind one shared lock.

    `snapshot()` returns a plain nested dict (JSON-ready), taken
    atomically so cross-metric invariants (admitted == completed +
    failed + ... after a drain) hold in a single read."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name, self._lock)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name, self._lock)
            return self._gauges[name]

    def histogram(self, name: str, cap: int = 2048,
                  buckets: Optional[List[float]] = None) -> Histogram:
        """Get-or-create histogram `name`. `buckets` (first creation
        only) arms exact cumulative bucket counts so `to_prometheus`
        exports a native histogram family next to the summary."""
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(name, self._lock,
                                                   cap, buckets=buckets)
            return self._histograms[name]

    def timer(self, name: str, record_event: bool = True) -> _Timer:
        """Time a block into histogram `name` and (by default) into a
        profiler RecordEvent span of the same name, so serving phases
        appear on the XPlane timeline next to the device steps."""
        span = None
        if record_event:
            from ..profiler import RecordEvent
            span = RecordEvent(name)
        return _Timer(self.histogram(name), span)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "histograms": {n: h.summary()
                               for n, h in self._histograms.items()},
            }

    def to_prometheus(self, prefix: str = "paddle_tpu_") -> str:
        """Render every metric in the Prometheus text exposition
        format (version 0.0.4) — the direct prerequisite for the
        multi-replica router's HTTP `/metrics` endpoint (ROADMAP
        direction 3): an HTTP handler returns exactly this string with
        content type ``text/plain; version=0.0.4``.

        Counters render as ``<prefix><name>_total``, gauges as
        ``<prefix><name>``, histograms as Prometheus *summaries*
        (``{quantile="0.5|0.9|0.95|0.99"}`` over the recent window,
        plus lifetime ``_sum`` / ``_count``). Registry names are
        sanitized to the Prometheus charset (``serving.step_s`` →
        ``serving_step_s``). One atomic snapshot backs the whole
        rendering, so cross-metric invariants hold within a scrape.

        Histograms created with a bucket ladder ADDITIONALLY export a
        native histogram family ``<prefix><name>_hist`` — cumulative
        ``_bucket{le="..."}`` series (lifetime-exact counts, ``+Inf``
        included) plus ``_hist_sum`` / ``_hist_count`` — so an
        external Prometheus can compute its own burn rates instead of
        trusting the in-process windowed quantiles. The ``_hist``
        suffix keeps the summary and histogram as two distinct
        families, which a strict 0.0.4 parser requires."""
        with self._lock:
            # ONE lock acquisition (RLock — snapshot() re-enters) for
            # the summary snapshot AND the bucket counts: an observe()
            # landing between two separate reads would render a finite
            # le bucket above the +Inf count — a non-monotone
            # histogram Prometheus rejects into NaN quantiles
            snap = self.snapshot()
            hist_buckets = {}
            for n, h in self._histograms.items():
                cum = h.buckets()
                if cum is not None:
                    hist_buckets[n] = cum
        lines: List[str] = []

        def san(name: str) -> str:
            return _PROM_NAME_RE.sub("_", name)

        def num(v) -> str:
            return repr(float(v))

        for name, v in snap["counters"].items():
            # the _total suffix is part of the family name in the
            # 0.0.4 text format — a TYPE line for the bare name would
            # leave the actual samples typed "unknown"
            base = prefix + san(name) + "_total"
            lines.append(f"# TYPE {base} counter")
            lines.append(f"{base} {num(v)}")
        for name, v in snap["gauges"].items():
            base = prefix + san(name)
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base} {num(v)}")
        for name, s in snap["histograms"].items():
            base = prefix + san(name)
            lines.append(f"# TYPE {base} summary")
            for q, key in ((0.5, "p50"), (0.9, "p90"),
                           (0.95, "p95"), (0.99, "p99")):
                if key in s:
                    lines.append(f'{base}{{quantile="{q}"}} {num(s[key])}')
            lines.append(f"{base}_sum {num(s.get('sum', 0.0))}")
            lines.append(f"{base}_count {num(s.get('count', 0))}")
            cum = hist_buckets.get(name)
            if cum is not None:
                hb = base + "_hist"
                lines.append(f"# TYPE {hb} histogram")
                for le, count in cum:
                    lines.append(
                        f'{hb}_bucket{{le="{le}"}} {num(count)}')
                lines.append(f'{hb}_bucket{{le="+Inf"}} '
                             f'{num(s.get("count", 0))}')
                lines.append(f"{hb}_sum {num(s.get('sum', 0.0))}")
                lines.append(f"{hb}_count {num(s.get('count', 0))}")
        return "\n".join(lines) + "\n"
