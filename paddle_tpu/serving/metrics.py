"""paddle_tpu.serving.metrics — lock-safe serving metrics.

Reference analog: PaddleNLP serving / FastDeploy expose Prometheus-style
counters (requests accepted/rejected, TTFT, inter-token latency, queue
depth, cache usage). Here the registry is in-process: counters, gauges
and histograms behind one lock, with a plain-dict `snapshot()` so tests,
benchmarks and an eventual HTTP frontend (ROADMAP open item) read one
consistent view without scraping.

Profiler integration: `MetricsRegistry.timer(name)` is a context manager
that both observes wall time into a histogram AND opens a
`paddle_tpu.profiler.RecordEvent` span, so engine phases (admission,
decode step) land in the same XPlane trace as the device work they
schedule.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry"]


class Counter:
    """Monotonic counter (requests_admitted, tokens_generated, ...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._value = 0
        self._lock = lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue_depth, kv_blocks_in_use, ...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, v: float) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Latency distribution (TTFT, queue wait, per-step decode time).

    Keeps a bounded ring of raw observations (default 2048): count/sum
    are exact over the histogram's lifetime, percentiles are over the
    most recent window — the steady-state view a serving dashboard
    wants, without unbounded memory on long-lived engines."""

    __slots__ = ("name", "_lock", "_ring", "_cap", "_count", "_sum",
                 "_min", "_max")

    def __init__(self, name: str, lock: threading.RLock, cap: int = 2048):
        self.name = name
        self._lock = lock
        self._ring: List[float] = []
        self._cap = cap
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            if len(self._ring) < self._cap:
                self._ring.append(v)
            else:
                self._ring[self._count % self._cap] = v
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)

    @staticmethod
    def _percentile(sorted_vals: List[float], q: float) -> float:
        # nearest-rank on the sorted window
        idx = min(len(sorted_vals) - 1,
                  max(0, int(round(q * (len(sorted_vals) - 1)))))
        return sorted_vals[idx]

    def percentile(self, q: float, since: int = 0) -> Optional[float]:
        """Nearest-rank percentile (q in [0, 1]) over the recent
        observation window, or None before the first observation —
        benchmark emitters read arbitrary quantiles (itl_ms_p99 & co)
        without re-implementing the windowing. `since` drops the first
        `since` lifetime observations (as counted by summary()["count"])
        from the window first, so a bench can rank only the samples
        recorded inside its timed region (e.g. skip the warmup request's
        compile-tainted inter-token gaps); observations that already
        fell off the ring are skipped implicitly."""
        with self._lock:
            if not self._ring:
                return None
            vals = self._ring
            if since > 0:
                if self._count <= self._cap:
                    ordered = vals
                else:
                    start = self._count % self._cap
                    ordered = vals[start:] + vals[:start]
                vals = ordered[max(0, since - (self._count - len(ordered))):]
                if not vals:
                    return None
            return self._percentile(sorted(vals), q)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            if not self._count:
                return {"count": 0}
            vals = sorted(self._ring)
            return {
                "count": self._count,
                "sum": self._sum,
                "mean": self._sum / self._count,
                "min": self._min,
                "max": self._max,
                "p50": self._percentile(vals, 0.50),
                "p90": self._percentile(vals, 0.90),
                "p95": self._percentile(vals, 0.95),
                "p99": self._percentile(vals, 0.99),
            }


class _Timer:
    """Wall-time span → histogram observation + profiler RecordEvent.
    The measured interval stays readable on `.elapsed` after exit so
    derived metrics share the one measurement."""

    __slots__ = ("_hist", "_span", "_t0", "elapsed")

    def __init__(self, hist: Histogram, span):
        self._hist = hist
        self._span = span
        self._t0 = None
        self.elapsed: Optional[float] = None

    def __enter__(self):
        if self._span is not None:
            self._span.begin()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        if self._span is not None:
            self._span.end()
        self._hist.observe(self.elapsed)
        return False


class MetricsRegistry:
    """Named counters/gauges/histograms behind one shared lock.

    `snapshot()` returns a plain nested dict (JSON-ready), taken
    atomically so cross-metric invariants (admitted == completed +
    failed + ... after a drain) hold in a single read."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name, self._lock)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name, self._lock)
            return self._gauges[name]

    def histogram(self, name: str, cap: int = 2048) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(name, self._lock, cap)
            return self._histograms[name]

    def timer(self, name: str, record_event: bool = True) -> _Timer:
        """Time a block into histogram `name` and (by default) into a
        profiler RecordEvent span of the same name, so serving phases
        appear on the XPlane timeline next to the device steps."""
        span = None
        if record_event:
            from ..profiler import RecordEvent
            span = RecordEvent(name)
        return _Timer(self.histogram(name), span)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "histograms": {n: h.summary()
                               for n, h in self._histograms.items()},
            }
