"""paddle_tpu.serving.frontend — stdlib-only asyncio HTTP frontend.

The HTTP layer of the "millions of users" serving tier (ROADMAP
direction 3): one `HttpFrontend` serves a `Router` (or a bare
`ServingEngine` — anything with submit/health/to_prometheus) over a
minimal asyncio HTTP/1.1 server built on `asyncio.start_server`. No
third-party dependencies, by design (the container bakes no web
framework): the request parser handles exactly what the endpoints
need — a request line, headers, a Content-Length or chunked body.

HTTP/1.1 semantics: connections are persistent by default (HTTP/1.0
clients opt in with ``Connection: keep-alive``) — the handler loops
requests on one socket until the client sends ``Connection: close``,
goes away, or a parse error makes further framing unsafe. Fixed-length
JSON responses carry Content-Length; SSE streams on a keep-alive
connection are framed with ``Transfer-Encoding: chunked`` and end with
the zero chunk, so the connection survives a completed stream. Chunked
REQUEST bodies are decoded too (same byte cap as fixed-length).

Endpoints:

  * ``POST /v1/generate`` — JSON in (``{"prompt": [ints], ...}``),
    JSON out (request id, replica, tokens, finish reason). Blocks the
    REQUEST, never the event loop: completion is awaited by polling
    the handle's append-only token list on the loop clock.
  * ``POST /v1/stream`` — Server-Sent Events: a ``routed`` event
    (request id + serving replica), one ``data:`` event per token as
    it streams, a terminal ``done``/``error`` event. Bridged from
    ``submit()``'s handle without blocking the event loop (the engine
    thread appends tokens; the handler drains new ones each tick and
    awaits the socket drain), so one slow client never stalls another.
  * ``GET /health`` — the router's aggregated worst-of status plus
    per-replica detail (and, with auto-restart on, the supervisor's
    per-slot SERVING/RESTARTING/FAILED lifecycle states); HTTP 200
    while at least one replica serves, 503 when none can — with a
    ``Retry-After: 1`` hint when a slot is RESTARTING (recovery is
    underway) and none when the fleet is breaker-pinned FAILED.
  * ``GET /metrics`` — `Router.to_prometheus()`: every replica's
    exposition merged with ``replica="rN"`` labels
    (``text/plain; version=0.0.4``) — including the SLO engine's
    ``slo_burn_rate_*`` gauges / ``slo_breaches_total`` counters and
    the native ``*_hist_bucket{le=...}`` latency histograms.
  * ``POST /admin/reset_breaker`` — operator recovery for a
    breaker-pinned FAILED slot (``{"slot": 1}`` or
    ``{"replica": "r1"}``): clears the crash-loop history and
    re-enters the supervisor's readiness-gated recovery cycle. 200
    with the slot's new state, 409 when the slot is not FAILED, 404
    for an unknown slot, 400 without a supervisor.
  * ``POST /debug/profile`` — on-demand device-time capture window
    (``{"steps": 8, "timeout_s": 30}``): fences the next K batcher
    ticks on every replica and returns the per-shape device-wall
    report (`Router.capture_profile`). The fenced steps also annotate
    the trace timelines with device wall next to host wall.

Backpressure and lifecycle: `NoReplicaAvailable`/`QueueFullError`
(every replica's admission queue rejected) maps to **429**, a prompt
that can never fit to 400, shutdown-in-progress to 503. `shutdown()`
drains gracefully: the listener closes, in-flight handlers finish
their requests, then the router shuts down (drain=True) underneath.
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional, Tuple

from .request import RequestState
from .scheduler import QueueFullError

__all__ = ["HttpFrontend"]

_MAX_BODY = 1 << 20          # 1 MiB request-body cap (413 past it)
_MAX_HEADER = 32 * 1024

_STATUS_TEXT = {200: "OK", 400: "Bad Request", 404: "Not Found",
                405: "Method Not Allowed", 408: "Request Timeout",
                413: "Payload Too Large", 429: "Too Many Requests",
                499: "Client Closed Request", 500: "Internal Server Error",
                503: "Service Unavailable", 504: "Gateway Timeout"}

# terminal request state -> HTTP status for the one-shot endpoint
_STATE_HTTP = {RequestState.FINISHED: 200, RequestState.TIMED_OUT: 504,
               RequestState.CANCELLED: 499, RequestState.FAILED: 500}


def _headers(status: int, ctype: str, length: Optional[int] = None,
             extra: str = "", *, keep: bool = False,
             chunked: bool = False) -> bytes:
    text = _STATUS_TEXT.get(status, "")
    head = (f"HTTP/1.1 {status} {text}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Connection: {'keep-alive' if keep else 'close'}\r\n"
            f"{extra}")
    if chunked:
        head += "Transfer-Encoding: chunked\r\n"
    if length is not None:
        head += f"Content-Length: {length}\r\n"
    return (head + "\r\n").encode()


def _json_body(status: int, payload: Dict[str, Any],
               extra: str = "", keep: bool = False) -> bytes:
    body = json.dumps(payload).encode()
    return _headers(status, "application/json", len(body), extra,
                    keep=keep) + body


def _chunk(data: bytes) -> bytes:
    """One chunked-transfer frame (hex size line + payload + CRLF)."""
    return f"{len(data):x}\r\n".encode() + data + b"\r\n"


def _sse_event(data: Dict[str, Any], event: Optional[str] = None) -> bytes:
    head = f"event: {event}\n" if event else ""
    return (head + f"data: {json.dumps(data)}\n\n").encode()


class HttpFrontend:
    """Asyncio HTTP server over a `Router` (stdlib only).

    Runs its own event loop on a background thread, so the serving
    stack stays usable from synchronous code and tests:

        fe = HttpFrontend(router, host="127.0.0.1", port=0)
        host, port = fe.start()          # port=0 → ephemeral, returned
        ...                              # POST /v1/generate, /v1/stream
        fe.shutdown()                    # drain handlers, then router

    `poll_s` is the token-bridge tick: how often a streaming handler
    checks the handle for new tokens (the engine thread appends them;
    the handler only ever reads — no cross-thread wakeups needed, and
    the event loop never blocks on engine work). `shutdown_router=False`
    leaves the router running after the HTTP layer stops."""

    def __init__(self, router, host: str = "127.0.0.1", port: int = 0,
                 *, poll_s: float = 0.005,
                 request_timeout_s: Optional[float] = 600.0,
                 shutdown_router: bool = True):
        self.router = router
        self._host = host
        self._port = port
        self._poll_s = float(poll_s)
        self._request_timeout_s = request_timeout_s
        self._shutdown_router = shutdown_router
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._draining = False
        self._active = 0                    # loop-thread only
        self._idle: Optional[asyncio.Event] = None
        self.address: Optional[Tuple[str, int]] = None

    # ---- lifecycle -------------------------------------------------------
    def start(self, timeout: float = 10.0) -> Tuple[str, int]:
        """Bind and serve on a background event-loop thread; returns
        the bound (host, port) — pass port=0 at construction for an
        ephemeral port."""
        if self._thread is not None:
            if not self._started.wait(timeout) or self.address is None:
                raise RuntimeError("frontend failed to start")
            return self.address
        self._thread = threading.Thread(target=self._run,
                                        name="paddle-tpu-http",
                                        daemon=True)
        self._thread.start()
        if not self._started.wait(timeout) or self.address is None:
            raise RuntimeError("frontend failed to start")
        return self.address

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        self._idle = asyncio.Event()
        self._idle.set()

        async def boot():
            self._server = await asyncio.start_server(
                self._handle, self._host, self._port)
            self.address = self._server.sockets[0].getsockname()[:2]
        try:
            loop.run_until_complete(boot())
        # ptlint: disable=EXC001 — bind failures (port in use) must
        # release start()'s waiter instead of hanging it; the error
        # surfaces as the RuntimeError start() raises on no address
        except Exception:
            self.address = None
            self._started.set()
            return
        self._started.set()
        try:
            loop.run_forever()
        finally:
            loop.close()

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = 30.0) -> bool:
        """Graceful stop: refuse new requests (503), wait for in-flight
        handlers to finish their responses (bounded by `timeout`), stop
        the loop, then shut the router down (drain semantics forwarded)
        unless `shutdown_router=False`."""
        clean = True
        if self._loop is not None and self._thread is not None \
                and self._thread.is_alive():
            fut = asyncio.run_coroutine_threadsafe(
                self._shutdown_async(drain, timeout), self._loop)
            try:
                clean = fut.result(None if timeout is None
                                   else timeout + 5.0)
            # ptlint: disable=EXC001 — a loop torn down mid-shutdown
            # must not leak out of the caller; the router still stops
            except Exception:
                clean = False
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(5.0)
            if self._thread.is_alive():
                clean = False
        if self._shutdown_router:
            if not self.router.shutdown(drain=drain, timeout=timeout):
                clean = False
        return clean

    async def _shutdown_async(self, drain: bool,
                              timeout: Optional[float]) -> bool:
        self._draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if drain and self._active:
            try:
                await asyncio.wait_for(self._idle.wait(), timeout)
            except asyncio.TimeoutError:
                return False
        return True

    def __enter__(self) -> "HttpFrontend":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ---- request handling ------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            # HTTP/1.1 keep-alive: loop requests on this connection
            # until the client asks for close, disconnects, or framing
            # breaks (a parse error leaves the stream position
            # unknowable — reuse would misparse, so those close).
            # The in-flight counter covers only the dispatch of each
            # request, never the idle park between them: a drain must
            # not wait on a keep-alive connection nobody is using.
            while True:
                try:
                    method, path, body, ka = \
                        await self._read_request(reader)
                except _HttpError as e:
                    writer.write(_json_body(e.status,
                                            {"error": e.message}))
                    await writer.drain()
                    return
                self._active += 1
                self._idle.clear()
                try:
                    if self._draining:
                        writer.write(_json_body(
                            503, {"error": "frontend is draining"}))
                        await writer.drain()
                        return
                    elif path == "/health" and method == "GET":
                        await self._health(writer, ka)
                    elif path == "/metrics" and method == "GET":
                        await self._metrics(writer, ka)
                    elif path == "/v1/generate" and method == "POST":
                        await self._generate(writer, body, ka)
                    elif path == "/v1/stream" and method == "POST":
                        await self._stream_sse(writer, body, ka)
                    elif path == "/admin/reset_breaker" \
                            and method == "POST":
                        await self._reset_breaker(writer, body, ka)
                    elif path == "/debug/profile" and method == "POST":
                        await self._profile(writer, body, ka)
                    elif path in ("/health", "/metrics", "/v1/generate",
                                  "/v1/stream", "/admin/reset_breaker",
                                  "/debug/profile"):
                        writer.write(_json_body(
                            405,
                            {"error": f"{method} not allowed on {path}"},
                            keep=ka))
                    else:
                        writer.write(_json_body(
                            404, {"error": f"no route for {path}"},
                            keep=ka))
                    await writer.drain()
                finally:
                    self._active -= 1
                    if self._active == 0:
                        self._idle.set()
                if not ka or writer.transport is None \
                        or writer.transport.is_closing():
                    return
        except (ConnectionError, asyncio.IncompleteReadError):
            pass                       # client went away mid-response
        # ptlint: disable=EXC001 — top-level handler boundary: an
        # unexpected error answers 500 on THIS connection instead of
        # killing the accept loop for every client
        except Exception as e:
            try:
                writer.write(_json_body(500, {"error": repr(e)}))
                await writer.drain()
            except (ConnectionError, RuntimeError):
                pass
        finally:
            try:
                writer.close()
            except RuntimeError:
                pass

    async def _read_request(self, reader) -> Tuple[str, str, bytes, bool]:
        """One request off the stream → (method, path, body,
        keep_alive). HTTP/1.1 defaults to keep-alive unless the client
        sends ``Connection: close``; HTTP/1.0 must opt in. The body is
        either Content-Length-framed or chunked-decoded."""
        try:
            head = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), self._request_timeout_s)
        except asyncio.TimeoutError:
            raise _HttpError(408, "timed out reading request head")
        except asyncio.LimitOverrunError:
            raise _HttpError(413, "request head too large")
        if len(head) > _MAX_HEADER:
            raise _HttpError(413, "request head too large")
        lines = head.decode("latin-1").split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) < 3:
            raise _HttpError(400, f"malformed request line: {lines[0]!r}")
        method, path = parts[0].upper(), parts[1].split("?", 1)[0]
        version = parts[-1].upper()
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        conn = headers.get("connection", "").lower()
        ka = (conn != "close" if version == "HTTP/1.1"
              else conn == "keep-alive")
        if "chunked" in headers.get("transfer-encoding", "").lower():
            try:
                body = await self._read_chunked(reader)
            except asyncio.TimeoutError:
                raise _HttpError(408, "timed out reading chunked body")
            except asyncio.IncompleteReadError:
                raise _HttpError(400, "truncated chunked body")
            return method, path, body, ka
        length = 0
        if "content-length" in headers:
            try:
                length = int(headers["content-length"])
            except ValueError:
                raise _HttpError(400, "bad Content-Length")
        if length > _MAX_BODY:
            raise _HttpError(413, f"body over {_MAX_BODY} bytes")
        body = b""
        if length:
            body = await asyncio.wait_for(reader.readexactly(length),
                                          self._request_timeout_s)
        return method, path, body, ka

    async def _read_chunked(self, reader) -> bytes:
        """Decode a chunked request body: hex-size-framed chunks up to
        the zero terminator (trailers skipped), with the same byte cap
        as fixed-length bodies."""
        body = b""
        while True:
            line = await asyncio.wait_for(reader.readline(),
                                          self._request_timeout_s)
            size_s = line.split(b";", 1)[0].strip()
            if not size_s:
                raise _HttpError(400, "missing chunk size")
            try:
                size = int(size_s, 16)
            except ValueError:
                raise _HttpError(400, f"bad chunk size: {size_s!r}")
            if size == 0:
                while True:          # optional trailers, then CRLF
                    t = await asyncio.wait_for(
                        reader.readline(), self._request_timeout_s)
                    if t in (b"\r\n", b"\n", b""):
                        return body
            if len(body) + size > _MAX_BODY:
                raise _HttpError(413, f"body over {_MAX_BODY} bytes")
            chunk = await asyncio.wait_for(
                reader.readexactly(size + 2), self._request_timeout_s)
            body += chunk[:-2]

    @staticmethod
    def _parse_submit(body: bytes) -> Dict[str, Any]:
        try:
            req = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            raise _HttpError(400, "body is not valid JSON")
        prompt = req.get("prompt")
        if not isinstance(prompt, list) or not prompt \
                or not all(isinstance(t, int) for t in prompt):
            raise _HttpError(
                400, "prompt must be a non-empty list of token ids")
        kw: Dict[str, Any] = {"prompt": prompt}
        for key, cast in (("priority", int), ("max_new_tokens", int),
                          ("stop_token_id", int), ("timeout_s", float)):
            if req.get(key) is not None:
                try:
                    kw[key] = cast(req[key])
                except (TypeError, ValueError):
                    raise _HttpError(400, f"bad {key}: {req[key]!r}")
        return kw

    def _submit(self, kw: Dict[str, Any]):
        """Route one parsed request; maps backpressure/validation onto
        HTTP errors. Submission is a queue push behind short locks —
        safe to run on the event loop directly."""
        prompt = kw.pop("prompt")
        try:
            return self.router.submit(prompt, **kw)
        except QueueFullError as e:       # incl. NoReplicaAvailable
            raise _HttpError(429, str(e))
        except ValueError as e:
            raise _HttpError(400, str(e))
        except RuntimeError as e:         # router/engine shutting down
            raise _HttpError(503, str(e))

    async def _generate(self, writer, body: bytes,
                        ka: bool = False) -> None:
        try:
            # ptlint: disable=ASYNC001 — queue push behind short locks (see _submit)
            req = self._submit(self._parse_submit(body))
        except _HttpError as e:
            writer.write(_json_body(e.status, {"error": e.message},
                                    keep=ka))
            return
        while not req.done:
            if writer.transport is None or writer.transport.is_closing():
                # client gave up: don't keep burning a batch slot and
                # KV blocks generating tokens nobody will read
                req.cancel()
                return
            await asyncio.sleep(self._poll_s)
        status = _STATE_HTTP.get(req.state, 500)
        writer.write(_json_body(status, {
            "request_id": req.request_id,
            "replica": getattr(req, "replica_id", None),
            "state": req.state.name,
            "finish_reason": req.finish_reason,
            "tokens": list(req.tokens),
            "failovers": getattr(req, "router_failovers", 0),
            "error": None if req.error is None else repr(req.error),
        }, keep=ka))

    async def _stream_sse(self, writer, body: bytes,
                          ka: bool = False) -> None:
        try:
            # ptlint: disable=ASYNC001 — queue push behind short locks (see _submit)
            req = self._submit(self._parse_submit(body))
        except _HttpError as e:
            writer.write(_json_body(e.status, {"error": e.message},
                                    keep=ka))
            return
        # keep-alive SSE is chunked-framed so the stream has an
        # in-band terminator (the zero chunk) and the connection
        # survives; a close-requested stream is close-delimited
        frame = _chunk if ka else (lambda b: b)
        writer.write(_headers(200, "text/event-stream",
                              extra="Cache-Control: no-cache\r\n",
                              keep=ka, chunked=ka))
        writer.write(frame(_sse_event(
            {"request_id": req.request_id,
             "replica": getattr(req, "replica_id", None)},
            event="routed")))
        await writer.drain()
        # the bridge: `req.tokens` is append-only (engine-thread
        # writes, this task reads a snapshot length) — each tick ships
        # the new suffix, and the terminal check runs only after a
        # tick that shipped nothing new, so no token can be lost
        sent = 0
        try:
            while True:
                if writer.transport is None \
                        or writer.transport.is_closing():
                    req.cancel()        # client went away mid-stream
                    return
                n = len(req.tokens)
                if n > sent:
                    for t in req.tokens[sent:n]:
                        writer.write(frame(_sse_event({"token": int(t)})))
                    sent = n
                    await writer.drain()
                    continue
                if req.done:
                    break
                await asyncio.sleep(self._poll_s)
        except ConnectionError:
            # the write path saw the disconnect first: stop generating
            # for a reader that no longer exists, then let _handle's
            # connection boundary swallow the error
            req.cancel()
            raise
        writer.write(frame(_sse_event(
            {"request_id": req.request_id,
             "replica": getattr(req, "replica_id", None),
             "state": req.state.name,
             "finish_reason": req.finish_reason,
             "tokens_generated": len(req.tokens),
             "failovers": getattr(req, "router_failovers", 0),
             "error": None if req.error is None else repr(req.error)},
            event="error" if req.state in (RequestState.FAILED,
                                           RequestState.TIMED_OUT)
            else "done")))
        if ka:
            writer.write(b"0\r\n\r\n")   # chunked terminator

    async def _health(self, writer, ka: bool = False) -> None:
        # ptlint: disable=ASYNC001 — point-in-time snapshot under short locks
        h = self.router.health()
        serving = h.get("serving_replicas",
                        0 if h.get("status") == "UNHEALTHY" else 1)
        if serving:
            writer.write(_json_body(200, h, keep=ka))
            return
        # nobody serves right now — but RESTARTING and FAILED are
        # different outages: a slot behind the supervisor's readiness
        # gate is coming back (tell the load balancer to retry soon),
        # a breaker-pinned FAILED fleet is not. The JSON body carries
        # the per-slot supervisor detail either way.
        extra = ("Retry-After: 1\r\n"
                 if h.get("restarting_replicas", 0) else "")
        writer.write(_json_body(503, h, extra=extra, keep=ka))

    async def _metrics(self, writer, ka: bool = False) -> None:
        # rendering fans out across every replica's counters (and for a
        # Router, walks each slot's engine under its lock) — heavy
        # enough to stall concurrent token streams if it ran on the
        # event loop, so it renders on the default executor instead
        loop = asyncio.get_running_loop()
        text = await loop.run_in_executor(None,
                                          self.router.to_prometheus)
        body = text.encode()
        writer.write(_headers(200, "text/plain; version=0.0.4",
                              len(body), keep=ka) + body)

    async def _reset_breaker(self, writer, body: bytes,
                             ka: bool = False) -> None:
        """Operator recovery: revive a breaker-pinned FAILED slot —
        `Router.reset_breaker` behind JSON. The slot re-enters the
        readiness-gated recovery cycle; it does NOT serve until the
        probe passes."""
        try:
            req = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            writer.write(_json_body(400,
                                    {"error": "body is not valid JSON"}, keep=ka))
            return
        slot = req.get("replica") if req.get("replica") is not None \
            else req.get("slot")
        if slot is None:
            writer.write(_json_body(
                400, {"error": "pass \"slot\" (index) or \"replica\" "
                               "(id like \"r1\")"}, keep=ka))
            return
        reset = getattr(self.router, "reset_breaker", None)
        if reset is None:
            writer.write(_json_body(
                400, {"error": "backend has no reset_breaker "
                               "(bare engine, not a Router)"}, keep=ka))
            return
        try:
            # blocking-safe: state flips under short locks plus a
            # thread spawn — no engine rebuild happens on this call
            # ptlint: disable=ASYNC001 — short-lock state flip, no engine rebuild
            out = reset(slot)
        except LookupError as e:
            writer.write(_json_body(404, {"error": str(e)}, keep=ka))
            return
        except RuntimeError as e:        # no supervisor attached
            writer.write(_json_body(400, {"error": str(e)}, keep=ka))
            return
        status = 200 if out.get("reset") else 409
        payload = {"ok": bool(out.get("reset")), **out}
        if status == 409:
            payload["error"] = (
                f"slot {out.get('replica')} is {out.get('state')}, "
                f"not FAILED — nothing to reset")
        writer.write(_json_body(status, payload, keep=ka))

    async def _profile(self, writer, body: bytes,
                       ka: bool = False) -> None:
        """On-demand device-time capture: arm + await the capture
        window WITHOUT blocking the event loop (the wait runs on the
        default executor — token streaming keeps flowing while the
        fenced steps run)."""
        try:
            req = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            writer.write(_json_body(400,
                                    {"error": "body is not valid JSON"}, keep=ka))
            return
        try:
            steps = int(req.get("steps", 8))
            timeout_s = float(req.get("timeout_s", 30.0))
        except (TypeError, ValueError):
            writer.write(_json_body(
                400, {"error": "steps must be an int, timeout_s a "
                               "number"}, keep=ka))
            return
        # hard caps: a capture window fences EVERY device call it
        # covers and the wait pins an executor thread — an unbounded
        # request could tax the whole fleet's latency indefinitely
        if not 1 <= steps <= 1024 or not 0 < timeout_s <= 300:
            writer.write(_json_body(
                400, {"error": "steps must be in [1, 1024] and "
                               "timeout_s in (0, 300]"}, keep=ka))
            return
        cap = getattr(self.router, "capture_profile", None)
        if cap is None:
            writer.write(_json_body(
                400, {"error": "backend has no capture_profile"}, keep=ka))
            return
        loop = asyncio.get_running_loop()
        report = await loop.run_in_executor(
            None, lambda: cap(steps=steps, timeout=timeout_s))
        writer.write(_json_body(200, report, keep=ka))


class _HttpError(Exception):
    """Internal: an HTTP error response (status + message) raised by
    parsing/submission helpers and rendered by the handler."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message
