"""paddle_tpu.serving.router — multi-replica routing over N ServingEngines.

The "millions of users" tier the ROADMAP's direction 3 names: one
`Router` owns N `ServingEngine` replicas (each with its own batcher,
KV block pool and prefix cache) and picks a replica per request by a
pluggable policy scoring

  * **health** — `engine.health()`: UNHEALTHY replicas are hard-excluded
    (a wedged engine thread serves nobody), DEGRADED ones are penalized
    but stay in rotation;
  * **occupancy** — `engine.load()`: admission-queue depth, in-flight
    count and KV block-pool utilization, so bursts spread instead of
    piling onto one pool;
  * **prefix affinity** — a router-level token-content prefix index
    (keys are pure token tuples over full KV blocks, exactly the PR 3
    `PrefixCacheIndex` keying): prefix siblings land on the replica
    already holding their blocks, so the per-replica prefix caches see
    hits instead of N cold copies of the same system prompt.

Cross-replica failover (the PR 8 follow-on): every client request is a
router-owned handle; the replica-side request streams into it through
an `on_token` bridge. When a replica flips UNHEALTHY (hung-step
watchdog) its stranded and quarantine-requeued requests FAIL with
`HungStepError` — the router re-admits each on a different healthy
replica with `prompt + tokens already streamed` (the PR 8
replica-agnostic resume mechanism), so the client's stream continues
where it stopped: streamed tokens are never re-emitted or lost, and
the pre-failover stream is a strict prefix of the final one.

Self-healing (PR 12): with `auto_restart=True` a `ReplicaSupervisor`
(`serving.supervisor`) watches every slot and closes the
detect→kill→respawn→re-warm→rejoin loop: an UNHEALTHY replica is torn
down and a fresh engine is rebuilt IN THE SAME SLOT (same
`replica_id`, from the router's retained params/cfg/per-replica
overrides), held off-rotation behind a readiness gate (AOT `warmup()`
plus a synthetic probe generation) until it proves it can serve, with
exponential backoff + jitter between failed attempts and a crash-loop
circuit breaker that pins a flapping slot FAILED. Affinity entries
pointing at the respawned slot are invalidated at swap (its KV pool
is empty) and re-learn from routed traffic.

Disaggregated serving (ROADMAP direction 2): `disaggregated=True`
routes admission to prefill-capable replicas and, when a prefill-role
replica finishes a request at "prefill_complete", migrates its
surrendered `serving.kvtransfer.KVSnapshot` to the decode-capable
replica the policy picks — imported with zero prefill chunks, the
stream strictly append-only across the hop, warm re-prefill as the
fallback rung. The same snapshot primitive rides failover: a replica
that died exporting its requests' KV (supervisor drain / respawn
failure) hands each survivor a warm resume instead of a re-prefill.

Lock order (LOCK001): `Router._lock` → `ServingEngine._lock` →
`AdmissionQueue._lock` — the router may call into an engine while
holding its own lock; no engine code path ever calls back into the
router. The supervisor thread takes `Router._lock` only for slot
state flips and the engine swap — all blocking work (teardown,
construction, warmup, probe, backoff waits) runs lock-free.

    router = Router(params, cfg, replicas=2, max_batch=4, ...)
    req = router.submit(prompt_ids)        # routed GenerationRequest
    for tok in req.stream(): ...
    router.health()                        # worst-of + per-replica
    router.to_prometheus()                 # per-replica exposition,
                                           # replica="rN" labels
    router.shutdown()                      # graceful drain

`serving.frontend.HttpFrontend` serves this object over HTTP.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .engine import EngineStopped, HungStepError
from .metrics import MetricsRegistry
from .request import GenerationRequest, RequestState
from .scheduler import QueueFullError
from .slo import rollup as slo_rollup

__all__ = ["Router", "NoReplicaAvailable", "default_policy"]

# default_policy weights: one queued-or-running request costs
# QUEUE_PENALTY, full KV-pool utilization costs UTIL_PENALTY, each
# affinity-matched full block earns AFFINITY_BLOCK_SCORE (capped at
# AFFINITY_BLOCK_CAP so a long warm prefix cannot justify an unbounded
# queue), and a DEGRADED replica pays DEGRADED_PENALTY — larger than
# the affinity cap, so a healthy cold replica always outranks a
# degraded warm one. A replica whose SLO verdict is WARN/BREACH pays
# SLO_WARN_PENALTY/SLO_BREACH_PENALTY — sized BETWEEN the occupancy
# weights and DEGRADED_PENALTY, so the policy steers load away from a
# burning replica before supervision has to act, but a breaching
# replica still outranks a DEGRADED one (SLOs degrade, health
# decides) and still serves when it is the only one left.
QUEUE_PENALTY = 0.5
UTIL_PENALTY = 2.0
AFFINITY_BLOCK_SCORE = 1.0
AFFINITY_BLOCK_CAP = 8
DEGRADED_PENALTY = 16.0
SLO_WARN_PENALTY = 4.0
SLO_BREACH_PENALTY = 10.0

_HEALTH_ORDER = {"HEALTHY": 0, "DEGRADED": 1, "UNHEALTHY": 2}

# role capability sets for disaggregated placement: admission may land
# on any prefill-capable replica, a KV migration may land on any
# decode-capable one. "both" replicas qualify for either side, so a
# mixed fleet (dedicated prefill + general-purpose) still routes.
_PREFILL_ROLES = ("prefill", "both")
_DECODE_ROLES = ("decode", "both")


class NoReplicaAvailable(QueueFullError):
    """Every replica either refused admission (queue full), stopped
    accepting, or is UNHEALTHY — the router-level backpressure signal
    (`serving.frontend` maps it to HTTP 429). Subclasses
    `QueueFullError` so engine-style backpressure handling composes."""


def default_policy(view: Dict[str, Any]) -> float:
    """Score one replica for one request (higher = better). `view` is
    the merged `engine.load()` + `engine.health()["status"]` dict plus
    `affinity_blocks`/`affinity_tokens` from the router's prefix index
    and `slo_verdict` (the replica's worst-of SLO verdict, "OK" when
    SLO tracking is off; UNHEALTHY replicas never reach the policy —
    the router hard-excludes them first). The default trades occupancy
    against prefix warmth: an affinity block outweighs up to two
    queued requests, a DEGRADED state outweighs the whole affinity
    cap, and a WARN/BREACH SLO verdict sits between the two — the
    policy sheds load off a burning replica before it degrades, yet a
    breaching replica still beats a DEGRADED one and still serves
    alone. Replace with any callable of the same shape via
    `Router(policy=...)`."""
    score = 0.0
    if view["status"] == "DEGRADED":
        score -= DEGRADED_PENALTY
    verdict = view.get("slo_verdict") or "OK"
    if verdict == "BREACH":
        score -= SLO_BREACH_PENALTY
    elif verdict == "WARN":
        score -= SLO_WARN_PENALTY
    score -= QUEUE_PENALTY * (view["queue_depth"] + view["in_flight"]
                              + view["parked_retries"])
    score -= UTIL_PENALTY * view["kv_utilization"]
    score += AFFINITY_BLOCK_SCORE * min(view["affinity_blocks"],
                                        AFFINITY_BLOCK_CAP)
    return score


class _AffinityNode:
    """One full block of an observed prefix chain: `key` is the block's
    token tuple, `replica` the index of the replica last routed a
    request carrying this prefix (last-writer-wins, so failover
    re-points siblings at the surviving replica), `parent` the
    children-dict this node lives in (unlink without a root walk)."""

    __slots__ = ("key", "replica", "children", "parent", "uid")

    def __init__(self, key: Tuple[int, ...], replica: int,
                 parent: Dict, uid: int):
        self.key = key
        self.replica = replica
        self.parent = parent
        self.uid = uid
        self.children: Dict[Tuple[int, ...], "_AffinityNode"] = {}


class _AffinityIndex:
    """Router-level prefix→replica index: a bounded trie over FULL-block
    token contents (the PR 3 keying — exact tuples, no hash aliasing)
    mapping each observed prefix block to the replica last routed a
    request carrying it. Unlike the per-replica `PrefixCacheIndex` this
    tracks no pool blocks and owns no refcounts — it only remembers
    *where* a prefix's KV is likely warm. FIFO-bounded at `cap` nodes:
    the oldest observation unlinks (descendants go unreachable and age
    out the same way, mirroring PrefixCacheIndex.evict's
    orphan-tolerant bookkeeping)."""

    def __init__(self, block_size: int, cap: int = 4096):
        self.bs = max(1, int(block_size))
        self.cap = max(1, int(cap))
        self._children: Dict[Tuple[int, ...], _AffinityNode] = {}
        self._order: "OrderedDict[int, _AffinityNode]" = OrderedDict()
        self._uid = 0

    def __len__(self) -> int:
        return len(self._order)

    def observe(self, tokens: Sequence[int], replica: int) -> None:
        """Record that `tokens`' full-block prefix chain was just routed
        to `replica` (creates missing nodes, re-points existing ones)."""
        children = self._children
        for i in range(len(tokens) // self.bs):
            key = tuple(tokens[i * self.bs:(i + 1) * self.bs])
            node = children.get(key)
            if node is None:
                node = _AffinityNode(key, int(replica), children, self._uid)
                children[key] = node
                self._order[self._uid] = node
                self._uid += 1
                while len(self._order) > self.cap:
                    _, old = self._order.popitem(last=False)
                    if old.parent.get(old.key) is old:
                        del old.parent[old.key]
            else:
                node.replica = int(replica)
            children = node.children

    def match(self, tokens: Sequence[int]) -> Dict[int, int]:
        """Matched-prefix tokens per replica: walk the longest recorded
        chain for `tokens` and credit each matched block's `block_size`
        tokens to the replica owning it (a chain re-pointed mid-way by
        failover credits both owners their share)."""
        out: Dict[int, int] = {}
        children = self._children
        for i in range(len(tokens) // self.bs):
            node = children.get(tuple(tokens[i * self.bs:(i + 1) * self.bs]))
            if node is None:
                break
            out[node.replica] = out.get(node.replica, 0) + self.bs
            children = node.children
        return out

    def invalidate(self, replica: int) -> int:
        """Drop every node pointing at `replica` — called when a slot's
        engine is respawned with an EMPTY KV pool: last-writer-wins
        re-pointing must not keep steering prefix siblings to a cold
        replica. Descendant nodes owned by other replicas may go
        unreachable and age out through the FIFO bound (the same
        orphan-tolerant bookkeeping eviction uses). Returns the number
        of nodes dropped; the index re-learns from routed traffic."""
        doomed = [uid for uid, node in self._order.items()
                  if node.replica == int(replica)]
        for uid in doomed:
            node = self._order.pop(uid)
            if node.parent.get(node.key) is node:
                del node.parent[node.key]
        return len(doomed)


class _Routed:
    """Router-side state of one in-flight request: the client-facing
    `outer` handle, the replica-side `inner` request currently serving
    it, the serving replica index, and the failover budget spent."""

    __slots__ = ("outer", "inner", "idx", "failovers", "user_on_token",
                 "total_new")

    def __init__(self, outer, inner, idx, user_on_token, total_new):
        self.outer = outer
        self.inner = inner
        self.idx = idx
        self.failovers = 0
        self.user_on_token = user_on_token
        self.total_new = total_new


def _default_failover_on(req: GenerationRequest,
                         error: Optional[BaseException],
                         reason: Optional[str]) -> bool:
    """The default failover predicate: re-admit on another replica only
    when the failure indicts the REPLICA, not the request — the
    hung-step watchdog's `HungStepError` terminals (stranded in-flight
    work and quarantine-requeued victims failed when the engine thread
    wedged), the fault-streak fuse's `fault_streak_engine_unhealthy`
    (queued/parked requests the broken replica never served — the
    replica died, not the request), and the restart pipeline's
    `drained_for_restart` / `respawn_failed` (the supervisor tore the
    replica down under the request, or could not resume its exported
    KV on the respawned engine — either way the replica ended it, and
    when a `kv_snapshot` rode down with the failure the failover
    re-places it warm). Convicted quarantine culprits, exhausted
    retries and on_token failures stay terminal: a request that
    poisons one replica would poison the next."""
    if reason in ("watchdog_hung_step", "watchdog_engine_unhealthy",
                  "fault_streak_engine_unhealthy",
                  "drained_for_restart", "respawn_failed"):
        return True
    return isinstance(error, HungStepError)


class Router:
    """N `ServingEngine` replicas behind one submit()/stream() surface.

    Construction: either pass `params, cfg` plus `replicas=N` and
    engine kwargs (each replica gets its own engine, `replica_id`
    "r0".."rN-1", `per_replica=[{...}, ...]` overrides individual
    replicas — e.g. a fault injector on one), or pass prebuilt
    `engines=[...]` (they must not be started yet). `warmup()`
    AOT-compiles every replica's ladder (before `start()`), `start()`
    launches the engine loops and the router's monitor thread.

    `submit()` routes by `policy` (default `default_policy`: health,
    occupancy, prefix affinity) and returns a router-owned
    `GenerationRequest` handle — `result()`, `stream()`, `cancel()`
    work exactly as on an engine-submitted request, across failovers.
    `failover=True` re-admits requests stranded on an UNHEALTHY
    replica onto a healthy one (resume from `prompt + tokens`; the
    predicate is pluggable via `failover_on`). Backpressure: when every
    replica refuses admission, `submit()` raises `NoReplicaAvailable`.

    `disaggregated=True` splits prefill from decode (ROADMAP direction
    2): admission routes to prefill-capable replicas
    (`role="prefill"`/"both"), and when a prefill-role replica finishes
    a request at "prefill_complete" the monitor migrates its exported
    `KVSnapshot` to the decode-capable replica the policy picks —
    imported there with zero prefill chunks, the client stream staying
    strictly append-only across the hop. A lost snapshot falls back to
    warm re-prefill on the decode side (the migrate→re-prefill ladder);
    the fleet must contain at least one prefill-capable and one
    decode-capable replica.

    `auto_restart=True` attaches a
    `serving.supervisor.ReplicaSupervisor`: an UNHEALTHY replica is
    torn down and respawned in its slot behind a readiness gate, with
    backoff + a crash-loop circuit breaker — knobs via
    `restart_opts={...}` (see `ReplicaSupervisor`). The rebuild recipe
    is the router's retained params/cfg/per-replica overrides for
    router-built replicas, or `engine_factory=` (a callable
    `i -> unstarted engine stamped replica_id=f"r{{i}}"`) — the hook
    that lets prebuilt `engines=` replicas respawn too. Requests
    stranded mid-restart ride the normal cross-replica failover.
    """

    def __init__(self, params=None, cfg=None, *, replicas: int = 2,
                 engines: Optional[Sequence] = None,
                 engine_factory: Optional[Callable[[int], Any]] = None,
                 policy: Optional[Callable[[Dict], float]] = None,
                 failover: bool = True,
                 max_failovers: Optional[int] = None,
                 failover_on: Optional[Callable] = None,
                 affinity_cap: int = 4096,
                 affinity_block_size: Optional[int] = None,
                 idle_poll_s: float = 0.01,
                 metrics: Optional[MetricsRegistry] = None,
                 start: bool = True,
                 per_replica: Optional[Sequence[Optional[Dict]]] = None,
                 disaggregated: bool = False,
                 auto_restart: bool = False,
                 restart_opts: Optional[Dict] = None,
                 clock: Callable[[], float] = time.monotonic,
                 **engine_kwargs):
        # retained rebuild recipe: the supervisor respawns a dead
        # replica IN ITS SLOT from exactly these (same replica_id, so
        # metrics/trace attribution stays stable across restarts)
        self._params, self._cfg = params, cfg
        self._engine_kwargs = dict(engine_kwargs)
        self._per_replica = (list(per_replica)
                             if per_replica is not None else None)
        # the PR 12 gap closed: `engine_factory(i)` is a pluggable
        # rebuild recipe — an UNSTARTED engine for slot i (it must
        # stamp replica_id=f"r{i}"; _build_replica enforces it).
        # Prebuilt engines= replicas can respawn through it, and when
        # given it also builds the initial fleet (engines=None,
        # params/cfg not required).
        self._engine_factory = engine_factory
        if engine_factory is not None and (engine_kwargs
                                           or per_replica is not None):
            # the factory IS the whole recipe — kwargs/overrides would
            # be silently dropped (it never reads them), so a fleet
            # "configured" that way must fail loudly at construction
            raise ValueError(
                "engine kwargs / per_replica do not apply with "
                "engine_factory= — fold the configuration into the "
                "factory itself")
        if engines is None:
            if (params is None or cfg is None) \
                    and engine_factory is None:
                raise ValueError(
                    "Router needs prebuilt engines=, an "
                    "engine_factory=, or params+cfg to build "
                    "replicas from")
            if replicas < 1:
                raise ValueError("replicas must be >= 1")
            engines = [self._build_replica(i)
                       for i in range(int(replicas))]
        else:
            if engine_kwargs or per_replica is not None:
                raise ValueError(
                    "engine kwargs only apply when the Router builds "
                    "the replicas itself (engines= was given)")
            if auto_restart and engine_factory is None:
                raise ValueError(
                    "auto_restart needs a rebuild recipe — pass "
                    "params+cfg (+ engine kwargs) instead of prebuilt "
                    "engines=, or give the prebuilt replicas an "
                    "engine_factory= to respawn through")
        self.engines: List = list(engines)
        if not self.engines:
            raise ValueError("Router needs at least one replica")
        self._disaggregated = bool(disaggregated)
        if self._disaggregated:
            roles = [getattr(e, "role", "both") for e in self.engines]
            if not any(r in _PREFILL_ROLES for r in roles) \
                    or not any(r in _DECODE_ROLES for r in roles):
                raise ValueError(
                    "disaggregated=True needs at least one "
                    "prefill-capable and one decode-capable replica "
                    f"(roles: {roles})")
        self.policy = policy or default_policy
        self._failover_enabled = bool(failover)
        self._max_failovers = (len(self.engines) - 1
                               if max_failovers is None
                               else int(max_failovers))
        self._failover_on = failover_on or _default_failover_on
        bs = affinity_block_size
        if bs is None:
            batcher = getattr(self.engines[0], "batcher", None)
            bs = getattr(batcher, "bs", 16)
        self._affinity = _AffinityIndex(bs, cap=affinity_cap)
        self._clock = clock
        self._idle_poll_s = float(idle_poll_s)
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._routed: Dict[str, _Routed] = {}       # router rid -> state
        self._rid_seq = 0
        self._accepting = True
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._failover_log: List[Dict] = []         # bounded forensics

        self.metrics = metrics or MetricsRegistry()
        m = self.metrics
        self._c_routed = m.counter("requests_routed")
        self._c_rejected = m.counter("requests_rejected_all_replicas")
        self._c_failovers = m.counter("failovers")
        self._c_failover_exhausted = m.counter("failovers_exhausted")
        self._c_monitor_errors = m.counter("router_monitor_errors")
        self._g_inflight = m.gauge("router_inflight")
        self._h_ttft = m.histogram("router_ttft_s")
        self._per_replica_routed = [
            m.counter(f"routed_{eng.replica_id}") for eng in self.engines]
        # self-healing surface: registered whether or not the
        # supervisor runs, so the Prometheus exposition is stable
        # (zeros mean "no restarts", absence would mean "old binary")
        self._c_restarts = m.counter("replica_restarts")
        self._c_restart_failures = m.counter("restart_failures")
        self._c_circuit_open = m.counter("circuit_open")
        # per-slot: restarts run concurrently (one supervisor thread
        # per slot), so a shared gauge would let one slot's recovery
        # zero out another slot's in-progress backoff
        self._g_restart_backoff = [
            m.gauge(f"restart_backoff_s_{eng.replica_id}")
            for eng in self.engines]
        # operator recovery surface: FAILED slots revived without a
        # process restart (POST /admin/reset_breaker)
        self._c_breaker_resets = m.counter("breaker_resets")
        # disaggregated / KV-transfer surface: `migrations` counts
        # every router-placed KVSnapshot import (prefill→decode
        # handoffs AND warm failovers), `migration_bytes` the KV
        # payload those moved; `handoff_s` times the prefill-complete
        # → decode-resumed gap (monitor-tick latency included — that
        # IS the handoff cost the client sees)
        self._c_migrations = m.counter("migrations")
        self._c_migration_bytes = m.counter("migration_bytes")
        self._h_handoff = m.histogram("handoff_s")
        self._migration_log: List[Dict] = []        # bounded forensics
        # fleet-wide SLO rollup: worst-of verdicts / max burn rates
        # exported with replica="router" next to the per-replica
        # series; the router's slo_breaches counter accumulates
        # per-ENGINE-INCARNATION deltas (keyed by engine identity —
        # a respawned replica's fresh tracker restarts at 0, and
        # diffing the GLOBAL sum would swallow real breaches until
        # the sum re-climbed past its old high-water mark)
        self._c_slo_breaches = m.counter("slo_breaches")
        self._slo_breach_marks: Dict[int, int] = {}
        self._supervisor = None
        if auto_restart:
            from .supervisor import ReplicaSupervisor   # lazy sibling
            self._supervisor = ReplicaSupervisor(
                self, clock=clock, **(restart_opts or {}))

        if start:
            self.start()

    def _build_replica(self, i: int):
        """Construct (never start) slot `i`'s engine from the retained
        params/cfg/engine kwargs + per-replica overrides — used for the
        initial build AND every supervisor respawn, so a respawned
        replica is configured exactly like the one it replaces
        (including its chaos injector, replica_id and metrics names).
        With an `engine_factory=` the factory IS the recipe (the
        prebuilt-engines respawn path); it must return an unstarted
        engine stamped replica_id=f"r{i}" — a mismatched id would
        corrupt per-replica metrics/trace attribution across the swap,
        so it raises here instead."""
        if self._engine_factory is not None:
            eng = self._engine_factory(i)
            if getattr(eng, "replica_id", None) != f"r{i}":
                raise ValueError(
                    f"engine_factory({i}) must stamp replica_id="
                    f"'r{i}', got {getattr(eng, 'replica_id', None)!r}"
                    f" — slot attribution would break across respawns")
            return eng
        from .engine import ServingEngine         # lazy: pulls nlp tree
        kw = dict(self._engine_kwargs)
        if self._per_replica is not None and self._per_replica[i]:
            kw.update(self._per_replica[i])
        kw.setdefault("replica_id", f"r{i}")
        kw["start"] = False
        return ServingEngine(self._params, self._cfg, **kw)

    # ---- lifecycle -------------------------------------------------------
    def warmup(self) -> int:
        """AOT-compile every replica's prefill/decode ladder (must run
        before `start()` — same rule as `ServingEngine.warmup`).
        Returns total shapes compiled across replicas."""
        return sum(eng.warmup() for eng in self.engines)

    def start(self) -> "Router":
        """Start every replica's engine loop plus the router monitor
        thread (terminal fan-in, cancellation forwarding, failover)
        and, with `auto_restart=True`, the replica supervisor."""
        with self._work:
            if self._stop:
                raise RuntimeError("router already shut down")
            if self._thread is None:
                for eng in self.engines:
                    eng.start()
                self._thread = threading.Thread(
                    target=self._monitor_loop,
                    name="paddle-tpu-router", daemon=True)
                self._thread.start()
        if self._supervisor is not None:
            self._supervisor.start()
        return self

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    @property
    def is_idle(self) -> bool:
        with self._lock:
            return not self._routed

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until no routed request is in flight anywhere; False
        on timeout."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._work:
            while self._routed:
                rem = self._idle_poll_s if deadline is None else \
                    min(self._idle_poll_s, deadline - self._clock())
                if rem <= 0:
                    return False
                self._work.wait(rem)
        return True

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> bool:
        """Stop the router. drain=True completes in-flight work first
        (failover stays armed during the drain); drain=False cancels
        everything. Replica engines shut down after the router-level
        drain, so a request mid-failover is not cut off by its new
        replica stopping underneath it."""
        clean = True
        with self._work:
            self._accepting = False
            self._work.notify_all()
        # supervisor first: it must not swap engines (or sit in a
        # backoff wait holding a half-built replica) while the
        # shutdown below walks the slot list; stop() interrupts an
        # in-flight restart at its next bounded wait and tears down
        # any engine it built but never swapped in
        if self._supervisor is not None:
            if not self._supervisor.stop(timeout=timeout):
                clean = False
        if drain and self._thread is not None:
            clean = self.drain(timeout)
        with self._work:
            self._stop = True
            self._work.notify_all()
        for eng in self.engines:
            if not eng.shutdown(drain=drain, timeout=timeout):
                clean = False
        if self._thread is not None:
            self._thread.join(2.0)
            if self._thread.is_alive():
                clean = False
        with self._work:
            for ent in list(self._routed.values()):
                if not ent.outer.done:
                    ent.outer._finish(RequestState.CANCELLED,
                                      "router_shutdown",
                                      now=self._clock())
            self._routed.clear()
            self._g_inflight.set(0)
            self._work.notify_all()
        return clean

    # ---- submission ------------------------------------------------------
    def submit(self, prompt, *, priority: int = 0,
               max_new_tokens: Optional[int] = None,
               stop_token_id: Optional[int] = None,
               timeout_s: Optional[float] = None,
               on_token=None) -> GenerationRequest:
        """Route and queue one request; returns the router-owned handle
        immediately. Raises `NoReplicaAvailable` when every replica
        refuses admission (backpressure — the frontend's 429),
        ValueError when the request can never fit a replica's pool, and
        RuntimeError after shutdown began."""
        outer = GenerationRequest(prompt, priority=priority,
                                  max_new_tokens=max_new_tokens,
                                  stop_token_id=stop_token_id,
                                  timeout_s=timeout_s)
        with self._work:
            if self._stop or not self._accepting:
                raise RuntimeError("router is shutting down")
            now = self._clock()
            outer.request_id = f"req{self._rid_seq}"
            self._rid_seq += 1
            outer.replica_id = None       # set by _place on success
            outer.router_failovers = 0
            outer.submit_time = now
            if timeout_s is not None:
                outer.deadline = now + timeout_s
            # state stamps BEFORE the engine sees the request: the
            # bridge's first-token PREFILL→DECODING transition races
            # the placement otherwise (a failed placement discards the
            # handle, so the early stamp can't leak a live PREFILL)
            outer.state = RequestState.PREFILL
            inner, idx = self._place(
                outer, on_token, exclude=(), tokens_kept=0,
                roles=_PREFILL_ROLES if self._disaggregated else None)
            ent = _Routed(outer, inner, idx, on_token,
                          inner.max_new_tokens)
            outer.max_new_tokens = inner.max_new_tokens
            self._routed[outer.request_id] = ent
            self._g_inflight.set(len(self._routed))
            self._work.notify_all()
        return outer

    def generate(self, prompt, timeout: Optional[float] = None,
                 **kw) -> List[int]:
        """Blocking one-shot through the router (cancel-on-timeout,
        like `ServingEngine.generate`)."""
        req = self.submit(prompt, **kw)
        try:
            return req.result(timeout)
        except TimeoutError:
            self.cancel(req)
            raise

    def stream(self, prompt, **kw):
        """Incremental one-shot: yields tokens as they stream (across
        failovers — the handle survives replica death)."""
        return self.submit(prompt, **kw).stream()

    def cancel(self, req: GenerationRequest) -> None:
        """Request cancellation; forwarded to the serving replica at
        the monitor's next tick (the handle's own `cancel()` reaches
        the same path)."""
        req.cancel()
        with self._work:
            self._work.notify_all()

    # ---- routing ---------------------------------------------------------
    def _views(self, eff: Sequence[int],
               exclude: Sequence[int],
               roles: Optional[Sequence[str]] = None,
               ) -> List[Tuple[float, int, Dict]]:
        """Policy-scored candidate replicas for a prompt, best first.
        UNHEALTHY / non-accepting / excluded replicas never appear;
        `roles` (disaggregated placement) restricts candidates to
        replicas whose `engine.role` is in the set."""
        aff = self._affinity.match(eff)
        out: List[Tuple[float, int, Dict]] = []
        sup = self._supervisor
        for i, eng in enumerate(self.engines):
            if i in exclude:
                continue
            if roles is not None \
                    and getattr(eng, "role", "both") not in roles:
                continue
            if sup is not None and not sup.slot_serving(i):
                # readiness gate: a RESTARTING slot (fresh engine still
                # warming / probing) or a breaker-pinned FAILED slot is
                # never offered to the policy
                continue
            h = eng.health()
            status = h["status"]
            if status == "UNHEALTHY":
                continue
            view = eng.load()
            if not view.get("accepting", True):
                continue
            view["status"] = status
            view["replica"] = i
            # SLO-aware routing: the replica's worst-of verdict rides
            # the policy view ("OK" when tracking is off or the engine
            # predates it) — evaluate() is cached per eval_every_s, so
            # this costs a dict read per candidate, not window math
            view["slo_verdict"] = (h.get("slo") or {}).get(
                "verdict", "OK")
            view["affinity_tokens"] = aff.get(i, 0)
            view["affinity_blocks"] = aff.get(i, 0) // self._affinity.bs
            out.append((float(self.policy(view)), i, view))
        # best score first; ties break toward the lower replica index
        out.sort(key=lambda t: (-t[0], t[1]))
        return out

    def _place(self, outer: GenerationRequest, user_on_token,
               exclude: Sequence[int],
               tokens_kept: int,
               roles: Optional[Sequence[str]] = None,
               snapshot=None) -> Tuple[GenerationRequest, int]:
        """Build the replica-side request for `outer`'s remaining work
        and submit it to the best-scoring replica that accepts
        (head-of-policy refusals fall through to the next candidate).
        With `snapshot` the placement imports the request's exported
        KV instead of enqueuing a prefill (`engine.submit_import`) —
        the inner request is pre-seeded with the already-streamed
        tokens, so the bridge only ever forwards NEW ones. Called
        under the router lock. Raises NoReplicaAvailable when nobody
        accepts."""
        eff = outer.prompt + outer.tokens
        remaining_new = (None if outer.max_new_tokens is None
                         else outer.max_new_tokens - len(outer.tokens))
        remaining_t = (None if outer.deadline is None
                       else max(0.001, outer.deadline - self._clock()))
        candidates = self._views(eff, exclude, roles=roles)
        last_err: Optional[BaseException] = None
        for score, i, view in candidates:
            eng = self.engines[i]
            if snapshot is not None:
                gen = snapshot.tokens[snapshot.prompt_len:]
                inner = GenerationRequest(
                    snapshot.tokens[:snapshot.prompt_len],
                    priority=outer.priority,
                    max_new_tokens=len(gen) + int(snapshot.budget),
                    stop_token_id=outer.stop_token_id,
                    timeout_s=remaining_t,
                    on_token=self._bridge(outer, user_on_token))
                # pre-seed the streamed suffix directly (not through
                # _deliver — these tokens already reached the client)
                inner.tokens = list(gen)
                try:
                    eng.submit_import(snapshot, inner)
                except (QueueFullError, EngineStopped, ValueError) as e:
                    # ValueError joins the fall-through set ONLY here:
                    # a fingerprint/pool mismatch indicts this replica
                    # for this snapshot (heterogeneous fleet), not the
                    # request — another candidate may still import it
                    last_err = e
                    continue
            else:
                inner = GenerationRequest(
                    eff, priority=outer.priority,
                    max_new_tokens=remaining_new,
                    stop_token_id=outer.stop_token_id,
                    timeout_s=remaining_t,
                    on_token=self._bridge(outer, user_on_token))
                try:
                    eng.submit(inner)
                except (QueueFullError, EngineStopped) as e:
                    # queue-full backpressure or a replica that stopped
                    # accepting between the view and the submit: fall
                    # through to the next candidate. Anything else — a
                    # ValueError for a request that can NEVER fit, or a
                    # genuine engine bug — propagates: rewriting it as
                    # backpressure would 429 a broken service
                    last_err = e
                    continue
            self._affinity.observe(eff, i)
            # the outer handle advertises its CURRENT serving replica
            # (updated on failover) — the frontend's SSE events and the
            # bench read it without reaching into router internals
            outer.replica_id = eng.replica_id
            self._c_routed.inc()
            self._per_replica_routed[i].inc()
            if eng.trace is not None and inner.trace_id is not None:
                eng.trace.emit(inner.trace_id, "routed",
                               replica=eng.replica_id,
                               score=round(score, 4),
                               router_rid=outer.request_id,
                               affinity_tokens=view["affinity_tokens"],
                               resumed_tokens=tokens_kept)
            return inner, i
        self._c_rejected.inc()
        raise NoReplicaAvailable(
            f"no replica accepted the request "
            f"({len(self.engines)} replicas, "
            f"{len(candidates)} eligible; last error: {last_err!r})")

    def _bridge(self, outer: GenerationRequest, user_on_token):
        """The replica→client token bridge: the inner request's
        on_token forwards each token into the outer handle's channel
        (append-only, so a failover's resume can never re-emit) and
        then the user callback. Runs on the serving replica's engine
        thread; a user-callback error fails the INNER request there —
        the engine's per-request boundary — and surfaces on the outer
        handle as a terminal FAILED, never a failover."""
        def fwd(tok: int) -> None:
            if outer.first_token_time is None:
                outer.first_token_time = self._clock()
                self._h_ttft.observe(
                    outer.first_token_time - outer.submit_time)
            outer._deliver(tok)
            if user_on_token is not None:
                user_on_token(tok)
        return fwd

    # ---- monitor thread --------------------------------------------------
    def _monitor_loop(self) -> None:
        while True:
            with self._work:
                if self._stop:
                    return
                self._sweep_locked()
                self._work.wait(self._idle_poll_s)

    def _sweep_locked(self) -> None:
        """One monitor tick: forward client cancellations to the
        serving replica, fan replica-side terminals into the outer
        handles, and fail over eligible failures to another replica.
        Per-entry exception boundary: a broken pluggable policy or
        failover predicate fails THAT request — it must never kill the
        monitor thread, which would wedge every handle forever."""
        done: List[str] = []
        for rid, ent in self._routed.items():
            try:
                if ent.outer.cancel_requested \
                        and not ent.inner.cancel_requested:
                    ent.inner.cancel()
                    self.engines[ent.idx].cancel(ent.inner)
                if ent.inner.done:
                    if self._handle_terminal(ent):
                        done.append(rid)
            # ptlint: disable=EXC001 — monitor boundary: the error is
            # attached to the request's handle and re-raised in its
            # result(); losing the monitor loop instead would silently
            # strand every in-flight and future request
            except Exception as e:
                self._c_monitor_errors.inc()
                if not ent.outer.done:
                    ent.outer._finish(RequestState.FAILED,
                                      "router_monitor_error", error=e,
                                      now=self._clock())
                done.append(rid)
        if done:
            for rid in done:
                del self._routed[rid]
            self._g_inflight.set(len(self._routed))
            self._work.notify_all()

    def _handle_terminal(self, ent: _Routed) -> bool:
        """Map one finished replica-side request onto its outer handle.
        Returns True when the outer is terminal (entry can drop), False
        when the request failed over and lives on elsewhere."""
        inner, outer = ent.inner, ent.outer
        now = self._clock()
        if self._disaggregated \
                and inner.state is RequestState.FINISHED \
                and inner.finish_reason == "prefill_complete" \
                and not outer.cancel_requested:
            # the disaggregated handoff: a prefill-role replica
            # finished its half and surrendered the KV — migrate to a
            # decode-capable replica (snapshot import, or warm
            # re-prefill when the export failed)
            if self._migrate(ent):
                return False
            outer._finish(RequestState.FAILED, "migration_failed",
                          error=inner.error, now=now)
            return True
        if inner.state is RequestState.FAILED and self._failover_enabled \
                and not outer.cancel_requested \
                and self._failover_on(inner, inner.error,
                                      inner.finish_reason):
            if ent.failovers < self._max_failovers:
                if self._failover(ent):
                    return False
            self._c_failover_exhausted.inc()
        outer._finish(inner.state, inner.finish_reason,
                      error=inner.error, now=now)
        return True

    def _migrate(self, ent: _Routed) -> bool:
        """Move `ent`'s prefill-complete request to a decode-capable
        replica: import the surrendered `KVSnapshot` when the prefill
        replica exported one (zero prefill chunks at the destination),
        else fall back to warm re-prefill from `prompt + tokens` — the
        migrate→re-prefill ladder. Returns False only when no decode
        replica accepts either form (the caller fails the outer)."""
        inner, outer = ent.inner, ent.outer
        from_idx = ent.idx
        from_id = self.engines[from_idx].replica_id
        t0 = (inner.finish_time if inner.finish_time is not None
              else self._clock())
        kept = len(outer.tokens)
        snap = getattr(inner, "kv_snapshot", None)
        inner2 = None
        idx = from_idx
        via = "kv_import"
        if snap is not None:
            try:
                inner2, idx = self._place(outer, ent.user_on_token,
                                          exclude=(from_idx,),
                                          tokens_kept=kept,
                                          roles=_DECODE_ROLES,
                                          snapshot=snap)
            except NoReplicaAvailable:
                inner2 = None
        if inner2 is None:
            via = "reprefill"
            try:
                inner2, idx = self._place(outer, ent.user_on_token,
                                          exclude=(from_idx,),
                                          tokens_kept=kept,
                                          roles=_DECODE_ROLES)
            except NoReplicaAvailable:
                return False
        inner.kv_snapshot = None          # drop the host payload
        ent.inner = inner2
        ent.idx = idx
        wall = max(0.0, self._clock() - t0)
        moved = snap.nbytes if (via == "kv_import") else 0
        blocks = snap.n_blocks if (via == "kv_import") else 0
        self._c_migrations.inc()
        if moved:
            self._c_migration_bytes.inc(moved)
        self._h_handoff.observe(wall)
        to_eng = self.engines[idx]
        entry = {"router_rid": outer.request_id,
                 "from_replica": from_id,
                 "to_replica": to_eng.replica_id,
                 "via": via, "bytes": moved, "blocks": blocks,
                 "tokens_kept": kept,
                 "handoff_s": round(wall, 6)}
        self._migration_log.append(entry)
        del self._migration_log[:-64]      # bounded forensics ring
        if to_eng.trace is not None:
            # span on the DESTINATION sink (it owns the request now);
            # dur is the client-visible prefill-complete→resumed gap
            to_eng.trace.span("migrated", dur=wall, **entry)
            if inner2.trace_id is not None:
                to_eng.trace.emit(inner2.trace_id, "migrated", **entry)
        return True

    def _failover(self, ent: _Routed) -> bool:
        """Re-admit `ent`'s request on a different healthy replica.
        When the dying replica attached an exported `kv_snapshot` to
        the failed inner (drain/teardown paths), the re-placement
        imports it — the survivor resumes decode with zero prefill
        chunks; otherwise it resumes from `prompt + tokens` (warm
        re-prefill). Either way nothing re-emits: the outer channel
        already holds every streamed token, and the resumed decode
        continues from exactly that suffix. Returns False when no
        replica accepts — the caller then finishes the outer with the
        original error."""
        outer = ent.outer
        from_idx = ent.idx
        from_id = self.engines[from_idx].replica_id
        kept = len(outer.tokens)
        roles = _DECODE_ROLES if self._disaggregated else None
        snap = getattr(ent.inner, "kv_snapshot", None)
        via = "reprefill"
        inner = None
        if snap is not None:
            try:
                inner, idx = self._place(outer, ent.user_on_token,
                                         exclude=(from_idx,),
                                         tokens_kept=kept,
                                         roles=roles, snapshot=snap)
                via = "kv_import"
            except NoReplicaAvailable:
                inner = None
        if inner is None:
            try:
                inner, idx = self._place(outer, ent.user_on_token,
                                         exclude=(from_idx,),
                                         tokens_kept=kept, roles=roles)
            except NoReplicaAvailable:
                return False
        ent.inner.kv_snapshot = None       # drop the host payload
        ent.inner = inner
        ent.idx = idx
        ent.failovers += 1
        outer.router_failovers = ent.failovers
        self._c_failovers.inc()
        if via == "kv_import":
            # a warm failover IS a migration: same primitive, same
            # accounting (the handoff histogram stays disagg-only —
            # failover latency is already visible in the failover log)
            self._c_migrations.inc()
            self._c_migration_bytes.inc(snap.nbytes)
        to_eng = self.engines[idx]
        entry = {"router_rid": outer.request_id,
                 "from_replica": from_id,
                 "to_replica": to_eng.replica_id,
                 "tokens_kept": kept, "via": via,
                 "failover_n": ent.failovers}
        self._failover_log.append(entry)
        del self._failover_log[:-64]       # bounded forensics ring
        if to_eng.trace is not None and inner.trace_id is not None:
            to_eng.trace.emit(inner.trace_id, "failover", **entry)
        return True

    # ---- operator recovery ----------------------------------------------
    def reset_breaker(self, slot) -> Dict:
        """Revive a breaker-pinned FAILED slot without a process
        restart (the PR 12 operator gap): clears the slot's crash-loop
        history and re-enters the normal RESTARTING → readiness-gate →
        SERVING recovery cycle. `slot` is a replica index or id
        ("r1"). Returns ``{"slot", "replica", "reset", "state"}`` —
        `reset` False when the slot was not FAILED (nothing to do).
        Raises RuntimeError without a supervisor (auto_restart off)
        and LookupError for an unknown slot. Bumps the
        `breaker_resets` counter and emits a `breaker_reset` trace
        event on success; `POST /admin/reset_breaker` on the frontend
        calls exactly this."""
        if self._supervisor is None:
            raise RuntimeError(
                "reset_breaker needs auto_restart=True — without a "
                "supervisor there is no breaker to reset")
        if isinstance(slot, str):
            idx = next((i for i, e in enumerate(self.engines)
                        if e.replica_id == slot), None)
            if idx is None:
                raise LookupError(f"unknown replica {slot!r}")
        else:
            idx = int(slot)
            if not 0 <= idx < len(self.engines):
                raise LookupError(
                    f"slot {idx} out of range "
                    f"[0, {len(self.engines)})")
        ok = self._supervisor.reset_breaker(idx)
        if ok:
            self._c_breaker_resets.inc()
            eng = self.engines[idx]
            if eng.trace is not None:
                # on the dead engine's sink: it is what the slot still
                # exports until the respawn swaps a fresh sink in
                eng.trace.span("breaker_reset", dur=0.0,
                               replica=eng.replica_id)
        return {"slot": idx, "replica": self.engines[idx].replica_id,
                "reset": ok,
                "state": self._supervisor.states()[idx]}

    def capture_profile(self, steps: int = 8,
                        timeout: Optional[float] = 30.0) -> Dict:
        """Fleet-wide device-time capture: arm EVERY replica's capture
        window (so the fences overlap instead of serializing), then
        wait for each to close (bounded by one shared `timeout` — an
        idle replica's report comes back ``complete`` False). Returns
        ``{replica_id: StepProfiler.report()}``; the frontend's
        ``POST /debug/profile`` returns exactly this."""
        for eng in self.engines:
            eng.batcher.profiler.arm_capture(steps)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        out: Dict[str, Dict] = {}
        for eng in self.engines:
            prof = eng.batcher.profiler
            while prof.capture_active():
                if deadline is not None and time.monotonic() > deadline:
                    # disarm the idle replica's leftover window: it
                    # must not fence future ticks nobody waits for
                    prof.cancel_capture()
                    break
                time.sleep(0.005)
            out[eng.replica_id] = prof.report()
        return out

    # ---- observability ---------------------------------------------------
    def _slo_rollup(self, per: Optional[List[Dict]] = None) -> Dict:
        """Fleet SLO aggregation (serving.slo.rollup) + the router-side
        Prometheus mirror: worst-of verdicts and max burn rates land in
        replica="router" gauges, and the router's monotonic
        slo_breaches counter accumulates per-incarnation deltas —
        each engine object's breach total is high-water-marked by
        identity, so a supervisor respawn (fresh tracker at 0) neither
        decrements the fleet counter nor swallows the NEXT real
        breaches behind the old global sum."""
        engines = list(self.engines)
        if per is None:
            per = [eng.health() for eng in engines]
        agg = slo_rollup([h.get("slo") for h in per])
        for name, o in agg["objectives"].items():
            self.metrics.gauge(
                f"slo_burn_rate_{name}").set(o["burn_rate_fast"])
        with self._lock:      # concurrent health()/scrape callers
            marks: Dict[int, int] = {}
            new = 0
            for eng, h in zip(engines, per):
                total = (h.get("slo") or {}).get("breaches_total", 0)
                seen = self._slo_breach_marks.get(id(eng), 0)
                new += max(0, total - seen)
                marks[id(eng)] = max(total, seen)
            self._slo_breach_marks = marks    # dead incarnations drop
            if new > 0:
                self._c_slo_breaches.inc(new)
        return agg

    def health(self) -> Dict:
        """Aggregated health: `status` is the WORST replica state (the
        conservative operator view), `serving_replicas` counts replicas
        still able to serve (in rotation AND not UNHEALTHY), and
        `replicas` carries each replica's full `engine.health()`
        detail keyed by replica id. With `auto_restart=True` the
        self-healing surface rides along: per-slot `supervisor` detail
        (state SERVING/RESTARTING/FAILED, restart + failure counts,
        current backoff, circuit-breaker flag), `restarting_replicas`
        / `failed_replicas` counts and the lifetime restart counters —
        so `/health` distinguishes a slot that is coming back from one
        that is permanently lost."""
        sup = self._supervisor
        states = sup.states() if sup is not None else None
        per = [eng.health() for eng in self.engines]
        worst = max(per, key=lambda h: _HEALTH_ORDER[h["status"]])
        out = {
            "status": worst["status"],
            "replica_count": len(per),
            "serving_replicas": sum(
                1 for i, h in enumerate(per)
                if h["status"] != "UNHEALTHY"
                and (states is None or states[i] == "SERVING")),
            "failovers": self._c_failovers.value,
            "migrations": self._c_migrations.value,
            "migration_bytes": self._c_migration_bytes.value,
            "requests_routed": self._c_routed.value,
            "requests_rejected": self._c_rejected.value,
            "replica_restarts": self._c_restarts.value,
            "restart_failures": self._c_restart_failures.value,
            "circuit_open": self._c_circuit_open.value,
            "restarting_replicas": (0 if states is None else
                                    states.count("RESTARTING")),
            "failed_replicas": (0 if states is None else
                                states.count("FAILED")),
            # fleet SLO verdict: worst-of per objective, max burn —
            # detail the /health JSON carries WITHOUT flipping the 200
            # (SLOs degrade, supervision decides)
            "slo": self._slo_rollup(per),
            "breaker_resets": self._c_breaker_resets.value,
            "replicas": {h["replica_id"]: h for h in per},
        }
        if sup is not None:
            out["supervisor"] = sup.info()
        return out

    def snapshot(self) -> Dict:
        """Router metrics + failover log + affinity-index size, plus
        every replica's full `engine.snapshot()` keyed by replica id."""
        with self._lock:
            snap = {
                "router": self.metrics.snapshot(),
                "failover_log": [dict(e) for e in self._failover_log],
                "migration_log": [dict(e) for e in self._migration_log],
                "disaggregated": self._disaggregated,
                "affinity_indexed_blocks": len(self._affinity),
                "supervisor": (None if self._supervisor is None
                               else self._supervisor.info()),
                "replicas": {},
            }
        for eng in self.engines:
            snap["replicas"][eng.replica_id] = eng.snapshot()
        return snap

    def to_prometheus(self, prefix: str = "paddle_tpu_") -> str:
        """Every replica's `MetricsRegistry.to_prometheus()` plus the
        router's own registry, merged into ONE valid exposition: each
        sample gains a `replica="rN"` label (`replica="router"` for
        router-level metrics) and samples are re-grouped per family so
        a strict parser sees each family exactly once — including the
        native-histogram `<name>_hist` families whose `_bucket{le=...}`
        samples must stay under THEIR OWN TYPE line, not the sibling
        summary's. The SLO rollup gauges refresh first, so a scrape
        always reads the current fleet burn rates."""
        self._slo_rollup()
        chunks = [("router", self.metrics.to_prometheus(prefix))]
        chunks += [(eng.replica_id, eng.metrics.to_prometheus(prefix))
                   for eng in self.engines]
        families: "OrderedDict[str, List[str]]" = OrderedDict()
        for rid, text in chunks:
            family = None
            for line in text.splitlines():
                if not line:
                    continue
                if line.startswith("# TYPE "):
                    family = line
                    families.setdefault(family, [])
                    continue
                if line.startswith("#"):
                    continue
                name, _, value = line.rpartition(" ")
                if "{" in name:
                    name = name[:-1] + f',replica="{rid}"}}'
                else:
                    name = name + f'{{replica="{rid}"}}'
                families.setdefault(family or "# TYPE _orphan untyped",
                                    []).append(f"{name} {value}")
        lines: List[str] = []
        for family, samples in families.items():
            lines.append(family)
            lines.extend(samples)
        return "\n".join(lines) + "\n"

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Merged Chrome-trace across replicas: each replica's sink
        exports on its own pid (process name carries the replica id),
        timestamps are aligned onto one global origin, and every
        event's `trace_id` arg is prefixed `rN:` so per-request rows
        stay unique across replicas in `tools/trace_report.py`."""
        sinks = [(i, eng) for i, eng in enumerate(self.engines)
                 if eng.trace is not None]
        if not sinks:
            return {"traceEvents": [], "displayTimeUnit": "ms"}
        origin = min(eng.trace.origin for _, eng in sinks)
        events: List[Dict[str, Any]] = []
        for i, eng in sinks:
            shift_us = (eng.trace.origin - origin) * 1e6
            pid = i + 1
            for e in eng.trace.to_chrome_trace()["traceEvents"]:
                e = dict(e)
                e["pid"] = pid
                if e.get("ph") == "M":
                    if e.get("name") == "process_name":
                        e["args"] = {
                            "name": f"paddle_tpu.serving {eng.replica_id}"}
                else:
                    e["ts"] = e.get("ts", 0.0) + shift_us
                args = e.get("args")
                if args and "trace_id" in args:
                    e["args"] = {
                        **args,
                        "trace_id": f"{eng.replica_id}:{args['trace_id']}"}
                events.append(e)
        return {"traceEvents": events, "displayTimeUnit": "ms"}
