"""paddle_tpu.serving.speculative — self-speculative decoding: config
validation + acceptance accounting for the draft-and-verify pipeline.

The device math lives in `nlp.paged` (`ContinuousBatcher(speculative=
True, spec_k=, draft_layers=)`); this module is the dependency-free
host half (stdlib only, like `serving.trace` / `serving.faults`), so
the batcher can hold the config and stats without pulling the serving
engine.

How self-speculation works (and why it needs no second weight set):
serving decode is memory-bound — every step sweeps the full weight
stack plus the live KV pool to emit ONE token per request. A draft
model proposing k tokens lets the target *verify* all k+1 positions in
one sweep instead; greedy verification accepts the longest prefix of
draft tokens that match the target's own greedy choices, plus one
corrected token, so the output is **provably identical to plain greedy
decoding** — speculation changes the schedule, never the tokens. The
draft here is the SAME model with a truncated layer stack
(`draft_layers=d`): because layer l's KV depends only on layers < l,
the target's committed pool layers 0..d-1 ARE the d-layer draft's KV
cache — the draft reads them for free and no second weight set or
cache exists.

Tree drafts (speculation v2): a single chain wastes the full-depth
verify sweep whenever its FIRST proposal misses. `tree=[b0, b1, ...]`
instead drafts b0 candidates for the next token, b1 children for each
of those, and so on — a token tree of sum(prod(b0..bj)) nodes packed
into one suffix slab, scored by ONE full-depth verify call whose
per-query visibility is the node→ancestor mask (each node sees the
committed pool plus exactly its own root-to-node path, so its verify
logits equal the sequential prefix's). Acceptance walks the tree level
by level following the target's greedy token; the longest accepted
path commits row-sequentially exactly like the chain, so the output
stays bit-identical to plain greedy decode and the int8 grow-only
scale / prefix-cache invariants carry over unchanged. Child 0 of every
node is the draft's own argmax, so the tree's candidate set contains
the chain's path — per sweep, tree acceptance >= chain acceptance at
equal draft depth.

Tensor parallel (PR 20): speculation composes with a TP mesh and with
the Pallas verify backend unchanged, because every spec operand already
shards along axes the mesh splits or replicates. The suffix slab's K/V
carry a kv-head axis, so they shard with the pool; the ancestor mask,
per-row base lengths and the accept walk's token comparisons are
head-free, so they replicate; and the verify's activation all-gather
reuses the output-split projection convention (serving/tp.py), which
never reassociates a contracted sum — so greedy output under mesh ×
speculation × pallas stays BIT-identical to unsharded plain decode.
The sharded kernel call itself is `shard_map`-wrapped in
nlp/ragged_attention.py; this module needs no mesh awareness beyond
`spec_attention_impl` riding the memo keys (`_skey`) so every
(mesh × impl × spec) combination AOT-lowers at warmup.

The verify-then-commit invariant: neither the draft nor the verify's
scoring pass writes the KV pool. Proposed tokens' per-layer K/V ride
an in-register slab; after acceptance is known (on device, same
compiled call) only the accepted rows are committed — written one row
at a time in order, so the int8 pool's grow-only per-block scales
evolve exactly as sequential decode's would. A rejected draft token
therefore never poisons the pool, the prefix cache, or a quantized
block's scale.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["SpecConfig", "SpecStats"]


class SpecConfig:
    """Validated self-speculative decoding configuration.

    `k` is the chain draft length (tokens proposed per verify sweep;
    the verify scores k+1 positions and emits between 1 and k+1
    tokens). `draft_layers` is the truncated draft depth — None drafts
    at full depth (the draft IS the target: acceptance ~100%, useful
    for parity tests and for benches on random-init models whose
    truncated drafts never agree with the target).

    `tree` switches to tree drafts: a branching spec like [3, 2, 1]
    proposes 3 candidates for the next token, 2 children under each of
    those, 1 under each of those — `k` is then DERIVED (the total node
    count, the per-sweep draft budget) and the chain `k` argument is
    ignored. `draft_w8` makes the draft sweep read an int8 weight-only
    quantization of the truncated layer stack (built once at batcher
    construction when the target serves fp weights; a no-op when the
    target already serves weight_dtype="int8") — drafting then costs
    int8 weight bytes. Verification always runs the target's own
    weights, so emitted tokens are unchanged either way."""

    def __init__(self, k: int = 4, draft_layers: Optional[int] = None,
                 *, num_layers: Optional[int] = None,
                 tree: Optional[Sequence[int]] = None,
                 draft_w8: bool = False):
        if tree is None:
            self.tree: Optional[Tuple[int, ...]] = None
            self.k = int(k)
            if self.k < 1:
                raise ValueError(f"spec_k must be >= 1, got {k}")
        else:
            self.tree = tuple(int(b) for b in tree)
            if not self.tree or any(b < 1 for b in self.tree):
                raise ValueError(
                    f"spec tree must be a non-empty sequence of "
                    f"positive branching factors, got {tree!r}")
            # the per-sweep draft budget: every node of the packed tree
            # is one proposed token (the equal-k-budget comparison the
            # bench's tree-vs-chain gate uses)
            self.k = sum(self.level_sizes()[1:])
        self.draft_w8 = bool(draft_w8)
        if draft_layers is None:
            self.draft_layers = None
        else:
            self.draft_layers = int(draft_layers)
            if self.draft_layers < 1:
                raise ValueError(
                    f"draft_layers must be >= 1, got {draft_layers}")
            if num_layers is not None and self.draft_layers > num_layers:
                raise ValueError(
                    f"draft_layers {self.draft_layers} exceeds the "
                    f"model's {num_layers} layers")

    # -- tree geometry (all static host math; () / chain answers keep
    #    the chain path byte-identical to before trees existed) --------
    def tree_depth(self) -> int:
        """Levels below the root (0 for a chain config)."""
        return 0 if self.tree is None else len(self.tree)

    def level_sizes(self) -> List[int]:
        """Node count per level, level 0 = the root (current token):
        n_0 = 1, n_j = n_{j-1} * tree[j-1]."""
        sizes = [1]
        for b in (self.tree or ()):
            sizes.append(sizes[-1] * b)
        return sizes

    def level_offsets(self) -> List[int]:
        """Suffix-slab row where each level starts (row 0 = root, then
        levels packed contiguously in order) — one entry per level plus
        the total row count at the end."""
        off = [0]
        for n in self.level_sizes():
            off.append(off[-1] + n)
        return off

    def slab_rows(self) -> int:
        """Packed-tree suffix-slab rows: root + every drafted node."""
        return 1 + self.k if self.tree is not None else self.k + 1

    def row_levels(self) -> List[int]:
        """Level of each slab row (0 for the root row)."""
        out: List[int] = []
        for lv, n in enumerate(self.level_sizes()):
            out.extend([lv] * n)
        return out

    def row_parents(self) -> List[int]:
        """Parent slab row of each slab row (the root points at
        itself): child i of level j (0-indexed within the level) hangs
        under node i // tree[j-1] of level j-1."""
        if self.tree is None:
            return [0] + list(range(self.k))  # chain: row r-1; root self
        sizes, offs = self.level_sizes(), self.level_offsets()
        parents = [0]
        for j in range(1, len(sizes)):
            b = self.tree[j - 1]
            parents.extend(offs[j - 1] + i // b for i in range(sizes[j]))
        return parents

    def ancestor_mask(self) -> List[List[bool]]:
        """A[p][s] = slab row s is an ancestor of row p or p itself —
        the packed tree's per-query visibility (each node attends to
        the committed pool plus exactly its root-to-node path, so its
        verify logits equal the sequential prefix's). Static per
        config; the device side uploads it as a constant."""
        parents = self.row_parents()
        S = len(parents)
        mask = [[False] * S for _ in range(S)]
        for p in range(S):
            s = p
            mask[p][p] = True
            while s > 0:
                s = max(parents[s], 0)
                mask[p][s] = True
        return mask

    def depth(self, num_layers: int) -> int:
        """The draft's resolved layer count (None -> full depth)."""
        return num_layers if self.draft_layers is None \
            else self.draft_layers

    def key(self, num_layers: int) -> tuple:
        """The spec-config element of every compiled-shape memo key:
        a spec batcher's executables must never be confused with a
        plain one's (zero post-warmup recompiles is gated per config).
        Chain configs keep the pre-tree 3-tuple byte-identical; a tree
        spec appends its branching factors and draft_w8 appends a
        marker, so every shape-bearing knob lands in the key."""
        base = ("spec", self.k, self.depth(num_layers))
        if self.tree is not None:
            base = base + ("tree",) + self.tree
        if self.draft_w8:
            base = base + ("w8",)
        return base

    def as_dict(self, num_layers: Optional[int] = None) -> Dict[str, Any]:
        d: Dict[str, Any] = {"k": self.k,
                             "draft_layers": self.draft_layers}
        if self.tree is not None:
            d["tree"] = list(self.tree)
        if self.draft_w8:
            d["draft_w8"] = True
        if num_layers is not None:
            d["draft_depth"] = self.depth(num_layers)
        return d


class SpecStats:
    """Host-side acceptance accounting for the spec pipeline (updated
    once per verify step from already-host values — no device syncs).

    `drafted` counts draft proposals, `accepted` the proposals the
    target's greedy verification kept, `emitted` the tokens actually
    landed per verify sweep (accepted prefix + the corrected token,
    truncated by budget / eos) — `tokens_per_step` > 1 is the whole
    point of speculation, `accept_rate` is the draft-quality signal.
    `depth_hist` distributes per-(sweep, slot) accepted path lengths —
    the data tree-shape tuning reads (a tree whose deep levels never
    accept is wasted verify width); the engine drains fresh depths into
    the `spec_accept_depth` Prometheus histogram."""

    def __init__(self):
        self.steps = 0          # verify sweeps executed
        self.slot_sweeps = 0    # (sweep, active slot) pairs
        self.drafted = 0        # draft tokens proposed
        self.accepted = 0       # draft tokens the target accepted
        self.emitted = 0        # tokens emitted by verify sweeps
        self.depth_hist: Dict[int, int] = {}   # accepted path length -> n
        self._fresh_depths: List[int] = []     # since the last drain

    def record_step(self, drafted: int, accepted: int, emitted: int,
                    slots: int = 1,
                    depths: Optional[Sequence[int]] = None) -> None:
        """Fold one verify sweep's counts in (host ints only);
        `slots` = active slots the sweep decoded, `depths` = each
        participating slot's accepted path length this sweep."""
        self.steps += 1
        self.slot_sweeps += int(slots)
        self.drafted += int(drafted)
        self.accepted += int(accepted)
        self.emitted += int(emitted)
        for d in (depths or ()):
            d = int(d)
            self.depth_hist[d] = self.depth_hist.get(d, 0) + 1
            self._fresh_depths.append(d)

    def drain_depths(self) -> List[int]:
        """Accepted-path depths recorded since the last drain — the
        engine's gauge sync feeds these to the Prometheus histogram
        exactly once each."""
        out, self._fresh_depths = self._fresh_depths, []
        return out

    def accept_rate(self) -> float:
        """Accepted / drafted (0.0 before any draft ran)."""
        return self.accepted / self.drafted if self.drafted else 0.0

    def tokens_per_step(self) -> float:
        """Tokens emitted per (sweep, slot) — directly comparable to
        plain decode's 1.0 per slot per step; the >1 multiplier the
        bench's --speculative gate asserts."""
        return self.emitted / self.slot_sweeps if self.slot_sweeps \
            else 0.0

    def accepted_per_sweep(self) -> float:
        """Accepted draft tokens per (sweep, slot) — the tree-vs-chain
        comparison at equal k-budget (tokens_per_step folds in the
        always-emitted corrected token; this isolates draft quality)."""
        return self.accepted / self.slot_sweeps if self.slot_sweeps \
            else 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "steps": self.steps, "slot_sweeps": self.slot_sweeps,
            "drafted": self.drafted,
            "accepted": self.accepted, "emitted": self.emitted,
            "accept_rate": round(self.accept_rate(), 4),
            "tokens_per_step": round(self.tokens_per_step(), 4),
            "accepted_per_sweep": round(self.accepted_per_sweep(), 4),
            "accept_depth_hist": {int(k): v for k, v in
                                  sorted(self.depth_hist.items())},
        }
