"""paddle_tpu.serving.speculative — self-speculative decoding: config
validation + acceptance accounting for the draft-and-verify pipeline.

The device math lives in `nlp.paged` (`ContinuousBatcher(speculative=
True, spec_k=, draft_layers=)`); this module is the dependency-free
host half (stdlib only, like `serving.trace` / `serving.faults`), so
the batcher can hold the config and stats without pulling the serving
engine.

How self-speculation works (and why it needs no second weight set):
serving decode is memory-bound — every step sweeps the full weight
stack plus the live KV pool to emit ONE token per request. A draft
model proposing k tokens lets the target *verify* all k+1 positions in
one sweep instead; greedy verification accepts the longest prefix of
draft tokens that match the target's own greedy choices, plus one
corrected token, so the output is **provably identical to plain greedy
decoding** — speculation changes the schedule, never the tokens. The
draft here is the SAME model with a truncated layer stack
(`draft_layers=d`): because layer l's KV depends only on layers < l,
the target's committed pool layers 0..d-1 ARE the d-layer draft's KV
cache — the draft reads them for free and no second weight set or
cache exists.

The verify-then-commit invariant: neither the draft nor the verify's
scoring pass writes the KV pool. Proposed tokens' per-layer K/V ride
an in-register slab; after acceptance is known (on device, same
compiled call) only the accepted rows are committed — written one row
at a time in order, so the int8 pool's grow-only per-block scales
evolve exactly as sequential decode's would. A rejected draft token
therefore never poisons the pool, the prefix cache, or a quantized
block's scale.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["SpecConfig", "SpecStats"]


class SpecConfig:
    """Validated self-speculative decoding configuration.

    `k` is the draft length (tokens proposed per verify sweep; the
    verify scores k+1 positions and emits between 1 and k+1 tokens).
    `draft_layers` is the truncated draft depth — None drafts at full
    depth (the draft IS the target: acceptance ~100%, useful for
    parity tests and for benches on random-init models whose truncated
    drafts never agree with the target)."""

    def __init__(self, k: int = 4, draft_layers: Optional[int] = None,
                 *, num_layers: Optional[int] = None):
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        if draft_layers is None:
            self.draft_layers = None
        else:
            self.draft_layers = int(draft_layers)
            if self.draft_layers < 1:
                raise ValueError(
                    f"draft_layers must be >= 1, got {draft_layers}")
            if num_layers is not None and self.draft_layers > num_layers:
                raise ValueError(
                    f"draft_layers {self.draft_layers} exceeds the "
                    f"model's {num_layers} layers")

    def depth(self, num_layers: int) -> int:
        """The draft's resolved layer count (None -> full depth)."""
        return num_layers if self.draft_layers is None \
            else self.draft_layers

    def key(self, num_layers: int) -> tuple:
        """The spec-config element of every compiled-shape memo key:
        a spec batcher's executables must never be confused with a
        plain one's (zero post-warmup recompiles is gated per config)."""
        return ("spec", self.k, self.depth(num_layers))

    def as_dict(self, num_layers: Optional[int] = None) -> Dict[str, Any]:
        d: Dict[str, Any] = {"k": self.k,
                             "draft_layers": self.draft_layers}
        if num_layers is not None:
            d["draft_depth"] = self.depth(num_layers)
        return d


class SpecStats:
    """Host-side acceptance accounting for the spec pipeline (updated
    once per verify step from already-host values — no device syncs).

    `drafted` counts draft proposals, `accepted` the proposals the
    target's greedy verification kept, `emitted` the tokens actually
    landed per verify sweep (accepted prefix + the corrected token,
    truncated by budget / eos) — `tokens_per_step` > 1 is the whole
    point of speculation, `accept_rate` is the draft-quality signal."""

    def __init__(self):
        self.steps = 0          # verify sweeps executed
        self.slot_sweeps = 0    # (sweep, active slot) pairs
        self.drafted = 0        # draft tokens proposed
        self.accepted = 0       # draft tokens the target accepted
        self.emitted = 0        # tokens emitted by verify sweeps

    def record_step(self, drafted: int, accepted: int, emitted: int,
                    slots: int = 1) -> None:
        """Fold one verify sweep's counts in (host ints only);
        `slots` = active slots the sweep decoded."""
        self.steps += 1
        self.slot_sweeps += int(slots)
        self.drafted += int(drafted)
        self.accepted += int(accepted)
        self.emitted += int(emitted)

    def accept_rate(self) -> float:
        """Accepted / drafted (0.0 before any draft ran)."""
        return self.accepted / self.drafted if self.drafted else 0.0

    def tokens_per_step(self) -> float:
        """Tokens emitted per (sweep, slot) — directly comparable to
        plain decode's 1.0 per slot per step; the >1 multiplier the
        bench's --speculative gate asserts."""
        return self.emitted / self.slot_sweeps if self.slot_sweeps \
            else 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "steps": self.steps, "slot_sweeps": self.slot_sweeps,
            "drafted": self.drafted,
            "accepted": self.accepted, "emitted": self.emitted,
            "accept_rate": round(self.accept_rate(), 4),
            "tokens_per_step": round(self.tokens_per_step(), 4),
        }
