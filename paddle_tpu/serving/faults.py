"""paddle_tpu.serving.faults — deterministic fault injection for the
serving stack.

The chaos harness behind the quarantine/retry/watchdog machinery: a
`FaultInjector` plugs into the ContinuousBatcher's device-call boundary
(`ContinuousBatcher(fault_injector=...)` /
`ServingEngine(fault_injector=...)`) and decides, per device call,
whether to raise an `InjectedFault`, sleep (a hung step), or pass.
Every decision is deterministic given the rule set and the seed, so a
chaos test or `bench_serving.py --chaos` run replays bit-identically.

The batcher calls `check(mode, rids)` once per REAL device-call tick
(mode "decode" | "fused" | "prefill", rids = every request riding the
call) and `check("probe", [rid], probe=True)` for each quarantine
re-execution probe. Probe calls do not advance the step counter and
only rid-scoped rules fire on them — so a step-scoped fault injected
once stays consumed during quarantine (fail-once-then-heal finds no
culprit and every suspect recovers), while a rid-scoped fault
reproduces under the probe and convicts exactly its request.

Dependency-free on purpose (stdlib only, like `serving.trace`):
`nlp.paged` may hold an injector without pulling jax or the engine.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["FaultInjector", "InjectedFault"]


class InjectedFault(RuntimeError):
    """Raised by a FaultInjector rule at the device-call boundary.

    `transient` marks failures the engine's retry predicate should
    treat as retryable (the default predicate checks exactly this
    attribute, plus RESOURCE_EXHAUSTED-shaped messages); `kind` names
    the injected failure class ("error" | "oom")."""

    def __init__(self, message: str, *, transient: bool = False,
                 kind: str = "error"):
        super().__init__(message)
        self.transient = transient
        self.kind = kind


class _Rule:
    """One injection rule: match fields + action + remaining budget."""

    __slots__ = ("action", "step", "rid", "rate", "after_step", "times",
                 "seconds", "transient", "kind", "message", "fired")

    def __init__(self, action: str, *, step: Optional[int] = None,
                 rid: Optional[int] = None, rate: Optional[float] = None,
                 after_step: int = 0, times: Optional[int] = 1,
                 seconds: float = 0.0, transient: bool = False,
                 kind: str = "error", message: Optional[str] = None):
        self.action = action          # "fail" | "hang"
        self.step = step
        self.rid = rid
        self.rate = rate
        self.after_step = int(after_step)
        self.times = times            # None = unlimited
        self.seconds = float(seconds)
        self.transient = bool(transient)
        self.kind = kind
        self.message = message
        self.fired = 0

    def exhausted(self) -> bool:
        return self.times is not None and self.fired >= self.times

    def describe(self) -> str:
        tgt = (f"step {self.step}" if self.step is not None
               else f"rid {self.rid}" if self.rid is not None
               else f"rate {self.rate}")
        return f"{self.kind} on {tgt}"


class FaultInjector:
    """Seedable, deterministic chaos harness for the batcher's
    device-call boundary.

    Arm rules (each returns `self` for chaining), wire the injector
    into a batcher or engine, and every matching device call fails or
    hangs exactly as armed:

        inj = (FaultInjector(seed=0)
               .fail_on_step(3, transient=True)     # fail-once-then-heal
               .fail_on_rid(7))                      # poison request 7
        eng = ServingEngine(..., fault_injector=inj)

    Rules: `fail_on_step(n)` fails the n-th real device call (1-based);
    `fail_on_rid(rid)` fails every call carrying `rid` (probes
    included — the quarantine convicts it); `hang_on_step(n, seconds)`
    sleeps inside the call boundary (trips the engine watchdog);
    `exhaust_on_step(n)` raises a RESOURCE_EXHAUSTED-style transient
    (allocator-pressure shape); `fail_rate(p)` fails a seeded `p`
    fraction of real calls. `times` bounds how often a rule fires
    (None = unlimited, default 1 except `fail_on_rid`); `after_step`
    delays rid/rate rules until the step counter passes it (mid-stream
    poison). `heal()` disarms everything; `stats()` reports calls seen
    and injections delivered. Thread-safe: tests arm rules from
    consumer threads while the engine thread steps."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._rules: List[_Rule] = []
        self.calls = 0                 # real device-call ticks seen
        self.probes = 0
        self.attachments = 0           # batchers this injector armed
        self._on_attach = None
        self._injected: Dict[str, int] = {}

    # ---- respawn chaos hook ---------------------------------------------
    def on_attach(self, callback) -> "FaultInjector":
        """Register `callback(injector, attach_count, replica_id)` to
        run every time a batcher wires this injector in — once at
        first construction and AGAIN for every supervisor respawn (a
        respawned replica re-applies its per-replica overrides, so the
        same injector instance follows the slot; `replica_id` names
        the attaching batcher, so one injector shared across replicas
        can still tell incarnations apart). The hook is how a chaos
        test poisons EVERY incarnation of a replica (e.g. re-arm a
        hang on the respawned engine's first device calls to drive
        the crash-loop circuit breaker open) instead of only the
        first. Step counters persist across attachments."""
        with self._lock:
            self._on_attach = callback
        return self

    def attach(self, replica_id: str = "r0") -> None:
        """Called by `ContinuousBatcher` when the injector is wired
        into a (possibly respawned) batcher: bumps `attachments` and
        fires the `on_attach` hook outside the lock (the hook arms
        rules, which takes the lock itself)."""
        with self._lock:
            self.attachments += 1
            cb, n = self._on_attach, self.attachments
        if cb is not None:
            cb(self, n, str(replica_id))

    # ---- arming ---------------------------------------------------------
    def _arm(self, rule: _Rule) -> "FaultInjector":
        with self._lock:
            self._rules.append(rule)
        return self

    def fail_on_step(self, n: int, *, times: int = 1,
                     transient: bool = False,
                     message: Optional[str] = None) -> "FaultInjector":
        """Fail the n-th real device call (1-based), `times` times."""
        return self._arm(_Rule("fail", step=int(n), times=times,
                               transient=transient, message=message))

    def fail_on_rid(self, rid: int, *, times: Optional[int] = None,
                    after_step: int = 0, transient: bool = False,
                    message: Optional[str] = None) -> "FaultInjector":
        """Fail every device call (probes included) carrying `rid` —
        unlimited by default: the persistent poisoned-request shape the
        quarantine exists to isolate. `after_step` arms it only once
        the real step counter passes that tick (mid-stream poison)."""
        return self._arm(_Rule("fail", rid=int(rid), times=times,
                               after_step=after_step, transient=transient,
                               message=message))

    def hang_on_step(self, n: int, seconds: float, *,
                     times: int = 1) -> "FaultInjector":
        """Sleep `seconds` inside the n-th real device call boundary —
        the injected hung step the engine watchdog must catch."""
        return self._arm(_Rule("hang", step=int(n), seconds=seconds,
                               times=times, kind="hang"))

    def hang_on_rid(self, rid: int, seconds: float, *,
                    times: int = 1) -> "FaultInjector":
        """Sleep `seconds` inside the next `times` device calls
        carrying `rid` — a mid-stream hang targeted at one request
        (arm it from an on_token callback once the rid is known)."""
        return self._arm(_Rule("hang", rid=int(rid), seconds=seconds,
                               times=times, kind="hang"))

    def exhaust_on_step(self, n: int, *, times: int = 1
                        ) -> "FaultInjector":
        """RESOURCE_EXHAUSTED-style allocator pressure at the n-th real
        device call: transient by construction (pressure passes), so
        the engine's default retry predicate re-admits the victims."""
        return self._arm(_Rule(
            "fail", step=int(n), times=times, transient=True, kind="oom",
            message="RESOURCE_EXHAUSTED: injected allocator pressure"))

    def fail_rate(self, p: float, *, times: Optional[int] = None,
                  after_step: int = 0,
                  transient: bool = True) -> "FaultInjector":
        """Fail a seeded `p` fraction of real device calls — the
        background-noise chaos mode (deterministic per seed)."""
        return self._arm(_Rule("fail", rate=float(p), times=times,
                               after_step=after_step, transient=transient))

    def heal(self) -> "FaultInjector":
        """Disarm every rule (armed state clears; counters survive)."""
        with self._lock:
            self._rules.clear()
        return self

    # ---- the boundary ---------------------------------------------------
    def check(self, mode: str, rids: Sequence[int],
              probe: bool = False) -> None:
        """The batcher's device-call gate: evaluate every armed rule
        against this call; raise `InjectedFault` or sleep on a match.
        `probe=True` marks a quarantine re-execution probe — it never
        advances the step counter and only rid-scoped rules fire."""
        rid_set = set(int(r) for r in rids)
        with self._lock:
            if probe:
                self.probes += 1
            else:
                self.calls += 1
            n = self.calls
            hang_s = 0.0
            fail: Optional[_Rule] = None
            for rule in self._rules:
                if rule.exhausted():
                    continue
                if rule.action == "fail" and fail is not None:
                    # one failure per call: later fail rules keep their
                    # budget (and stats stay injections == faults
                    # delivered) instead of being silently consumed
                    continue
                if probe:
                    hit = rule.rid is not None and rule.rid in rid_set
                else:
                    if n <= rule.after_step:
                        continue
                    hit = ((rule.step is not None and rule.step == n)
                           or (rule.rid is not None and rule.rid in rid_set)
                           or (rule.rate is not None
                               and self._rng.random() < rule.rate))
                if not hit:
                    continue
                rule.fired += 1
                self._injected[rule.kind] = \
                    self._injected.get(rule.kind, 0) + 1
                if rule.action == "hang":
                    hang_s = max(hang_s, rule.seconds)
                elif fail is None:
                    fail = rule
        # sleep OUTSIDE the lock: a hung call must not also wedge every
        # concurrent arm()/stats() caller
        if hang_s > 0.0:
            time.sleep(hang_s)
        if fail is not None:
            msg = fail.message or (
                f"injected fault ({fail.describe()}) at {mode} call {n} "
                f"rids={sorted(rid_set)}")
            raise InjectedFault(msg, transient=fail.transient,
                                kind=fail.kind)

    def stats(self) -> Dict[str, Any]:
        """Calls seen and injections delivered, per fault kind."""
        with self._lock:
            return {"calls": self.calls, "probes": self.probes,
                    "attachments": self.attachments,
                    "injected": dict(self._injected),
                    "armed_rules": sum(1 for r in self._rules
                                       if not r.exhausted())}
