"""paddle_tpu.serving.slo — in-process SLO engine for the serving tier.

The serving stack measures everything (PR 7 histograms, PR 11 router
counters) but until now nothing in-process *watched* the objectives the
`--load` bench leg reports: a TTFT regression or a goodput collapse was
visible only to whoever read the dashboard. The `SloTracker` closes
that loop — declarative objectives, evaluated continuously over dual
rolling windows, producing burn rates and OK / WARN / BREACH verdicts
the engine exposes through `health()["slo"]`, Prometheus
(`slo_burn_rate_*` gauges, `slo_breaches_total` counters) and TraceSink
`slo_breach` events, and that the Router aggregates fleet-wide.

Objectives are `{name: target}` pairs drawn from a fixed vocabulary
(unknown names raise — a typo'd objective silently never firing is the
worst possible failure mode for an alerting primitive):

  * ``ttft_s_p99``       — ceiling on p99 time-to-first-token (s);
  * ``itl_ms_p99``       — ceiling on p99 inter-token latency (ms);
  * ``queue_wait_s_p99`` — ceiling on p99 admission queue wait (s);
  * ``error_rate``       — ceiling on failed+timed-out / terminal
    requests (cancellations are the client's choice, not an error);
  * ``goodput_tok_s``    — FLOOR on generated tokens per second of
    the window's ACTIVE span (first in-window sample → now, so
    pre-traffic idle never dilutes real throughput into a phantom
    burn; an entirely idle window is "no evidence", not a breach).

Dual rolling windows (Google SRE multi-window burn-rate alerting,
shrunk to in-process scale): a fast window (~5 s) that reacts to an
incident within seconds, and a slow window (~60 s) that keeps the
verdict honest about sustained degradation after the fast window
forgets. The **burn rate** is how hard an objective is being consumed:
``value / target`` for ceilings, ``target / value`` for floors — 1.0
exactly at the objective, 2.0 means twice as bad as promised.

Verdicts per objective, with breach→recover hysteresis so a burn rate
oscillating around 1.0 cannot flap alerts:

    OK ──(fast burn >= breach_burn)──▶ BREACH
    BREACH stays BREACH until fast burn <= recover_burn, then
    ▶ WARN while (fast burn >= warn_burn OR slow burn >= breach_burn)
    ▶ OK otherwise

SLOs degrade, supervision decides: a BREACH never flips `/health` off
200 by itself — the verdict is detail for operators and load
balancers, while the PR 12 supervisor keeps deciding what gets
restarted.

Fake-clock-testable and dependency-free (stdlib only, like
`serving.trace`): the tracker takes an injectable `clock`, samples are
timestamped host floats, and evaluation is pure window math — no jax,
no device values (SYNC001 polices the record/evaluate helpers).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["SloTracker", "DEFAULT_OBJECTIVES", "OBJECTIVE_KINDS",
           "rollup", "worst_verdict"]

# Verdict severity order (worst last) — rollup() and the per-objective
# state machine both rank with this.
_VERDICT_ORDER = ("OK", "WARN", "BREACH")

# objective name -> (kind, sample stream) — the fixed vocabulary.
# "ceiling" objectives burn as value/target, "floor" ones as
# target/value; the stream names the sample series the value is
# computed from (see SloTracker.record_*).
OBJECTIVE_KINDS: Dict[str, Tuple[str, str]] = {
    "ttft_s_p99": ("ceiling", "ttft_s"),
    "itl_ms_p99": ("ceiling", "itl_s"),
    "queue_wait_s_p99": ("ceiling", "queue_wait_s"),
    "error_rate": ("ceiling", "requests"),
    "goodput_tok_s": ("floor", "tokens"),
}

# Generous catch-fire defaults: an unconfigured engine should page on
# "clearly broken", not on workload-specific tuning the operator never
# did. goodput_tok_s is absent on purpose — a throughput floor is
# meaningless without knowing the offered load.
DEFAULT_OBJECTIVES: Dict[str, float] = {
    "ttft_s_p99": 5.0,
    "itl_ms_p99": 500.0,
    "queue_wait_s_p99": 2.0,
    "error_rate": 0.05,
}


def worst_verdict(verdicts: Sequence[str]) -> str:
    """The most severe of a set of OK/WARN/BREACH verdicts (OK when
    the set is empty — no objective, nothing to breach)."""
    worst = "OK"
    for v in verdicts:
        if _VERDICT_ORDER.index(v) > _VERDICT_ORDER.index(worst):
            worst = v
    return worst


def _p99(vals: List[float]) -> float:
    """Nearest-rank p99 (matches Histogram._percentile's convention)."""
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(0.99 * (len(s) - 1)))))
    return s[idx]


class SloTracker:
    """Declarative SLO evaluation over dual rolling windows.

    Usage (the engine wires this automatically — `ServingEngine(
    slo_objectives={...})`):

        slo = SloTracker({"ttft_s_p99": 0.5, "goodput_tok_s": 100.0})
        slo.record_ttft(0.12); slo.record_tokens(8)
        ...
        report = slo.evaluate()     # cached, recomputed every
                                    # eval_every_s at most
        report["verdict"]           # "OK" | "WARN" | "BREACH"
        report["objectives"]["ttft_s_p99"]["burn_rate_fast"]

    `record_*` calls are hot-path cheap: one timestamped append to a
    bounded deque under the tracker lock. `evaluate()` prunes samples
    past the slow window and computes each objective's fast/slow value,
    burn rates and verdict (with hysteresis — see the module
    docstring); results are cached for `eval_every_s` so a router
    polling `health()` per routing decision never pays repeated window
    math. `pop_transitions()` drains the breach/recover edges since
    the last call — the engine turns them into TraceSink `slo_breach`
    events and counter bumps exactly once per transition.
    """

    def __init__(self, objectives: Optional[Dict[str, float]] = None,
                 *, fast_window_s: float = 5.0,
                 slow_window_s: float = 60.0,
                 warn_burn: float = 0.75, breach_burn: float = 1.0,
                 recover_burn: Optional[float] = None,
                 eval_every_s: float = 0.25, max_samples: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        objectives = dict(DEFAULT_OBJECTIVES if objectives is None
                          else objectives)
        for name, target in objectives.items():
            if name not in OBJECTIVE_KINDS:
                raise ValueError(
                    f"unknown SLO objective {name!r} — known: "
                    f"{sorted(OBJECTIVE_KINDS)}")
            if not (isinstance(target, (int, float)) and target > 0):
                raise ValueError(
                    f"objective {name!r} target must be a positive "
                    f"number, got {target!r}")
        self.objectives = objectives
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        if self.slow_window_s < self.fast_window_s:
            raise ValueError("slow_window_s must be >= fast_window_s")
        self.warn_burn = float(warn_burn)
        self.breach_burn = float(breach_burn)
        # hysteresis: once BREACH, stay until the fast burn drops to
        # recover_burn (default: the warn threshold) — a burn rate
        # oscillating around 1.0 must not flap breach events
        self.recover_burn = float(warn_burn if recover_burn is None
                                  else recover_burn)
        self._eval_every_s = float(eval_every_s)
        self._clock = clock
        self._lock = threading.Lock()
        # one bounded (t, value) ring per sample stream; pruned past
        # the slow window at evaluation time
        self._samples: Dict[str, deque] = {
            s: deque(maxlen=int(max_samples))
            for s in ("ttft_s", "itl_s", "queue_wait_s", "requests",
                      "tokens")}
        self._state: Dict[str, str] = {n: "OK" for n in objectives}
        self.breaches_total = 0
        self._transitions: List[Dict[str, Any]] = []
        self._cached: Optional[Dict[str, Any]] = None
        self._cached_at: Optional[float] = None

    # ---- recording (hot path: one bounded append under the lock) --------
    def _record(self, stream: str, value: float) -> None:
        with self._lock:
            self._samples[stream].append((self._clock(), float(value)))

    def record_ttft(self, seconds: float) -> None:
        """One request's time-to-first-token (seconds)."""
        self._record("ttft_s", seconds)

    def record_itl(self, seconds: float) -> None:
        """One inter-token gap (seconds — the itl_ms_p99 objective
        converts to ms at evaluation time)."""
        self._record("itl_s", seconds)

    def record_queue_wait(self, seconds: float) -> None:
        """One request's admission queue wait (seconds)."""
        self._record("queue_wait_s", seconds)

    def record_tokens(self, n: int) -> None:
        """Tokens generated by one dispatch (feeds the goodput floor)."""
        self._record("tokens", n)

    def record_request(self, error: bool) -> None:
        """One terminal request: error=True for FAILED / TIMED_OUT,
        False for FINISHED. Cancellations are not recorded — a client
        hanging up is not the server missing its objective."""
        self._record("requests", 1.0 if error else 0.0)

    # ---- evaluation ------------------------------------------------------
    def _window(self, stream: str,
                since: float) -> List[Tuple[float, float]]:
        return [(t, v) for t, v in self._samples[stream] if t >= since]

    def _value(self, name: str, window_s: float,
               now: float) -> Optional[float]:
        """One objective's observed value over the trailing `window_s`
        (None = no samples — evaluates as burn 0, verdict OK).

        The goodput floor measures rate over the window's ACTIVE span:
        tokens divided by (now - first in-window sample), not by the
        full window — a window straddling pre-traffic idle (engine
        warmup, a quiet period before a burst) must not dilute real
        throughput into a phantom burn. The span keeps growing while
        delivery stalls with samples still in the window (a genuine
        slowdown decays the rate), and an entirely idle window is None
        (no demand evidence — a floor cannot distinguish "no traffic"
        from "serving nothing"; pair it with the itl/ttft ceilings for
        stall detection)."""
        kind, stream = OBJECTIVE_KINDS[name]
        samples = self._window(stream, now - window_s)
        if not samples:
            return None
        vals = [v for _, v in samples]
        if name == "error_rate":
            return sum(vals) / len(vals)
        if name == "goodput_tok_s":
            span = max(now - samples[0][0], 1e-3)
            return sum(vals) / span
        p99 = _p99(vals)
        return p99 * 1000.0 if name == "itl_ms_p99" else p99

    def _burn(self, name: str, value: Optional[float]) -> float:
        if value is None:
            return 0.0
        target = self.objectives[name]
        kind, _ = OBJECTIVE_KINDS[name]
        if kind == "ceiling":
            return value / target
        # floor: burning means delivering LESS than promised
        return target / value if value > 0 else float("inf")

    def _verdict_locked(self, name: str, burn_fast: float,
                 burn_slow: float) -> str:
        prev = self._state[name]
        if burn_fast >= self.breach_burn:
            return "BREACH"
        if prev == "BREACH" and burn_fast > self.recover_burn:
            return "BREACH"            # hysteresis band: hold the alert
        if burn_fast >= self.warn_burn or burn_slow >= self.breach_burn:
            return "WARN"
        return "OK"

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.slow_window_s
        for ring in self._samples.values():
            while ring and ring[0][0] < horizon:
                ring.popleft()

    def evaluate(self, force: bool = False) -> Dict[str, Any]:
        """The tracker's verdict: per-objective fast/slow values, burn
        rates and OK/WARN/BREACH (worst-of under "verdict"), plus the
        lifetime breach counter. Cached for `eval_every_s` unless
        `force` — a router polling health() per routing decision pays
        one dict copy, not repeated window math."""
        with self._lock:
            now = self._clock()
            if (not force and self._cached is not None
                    and now - self._cached_at < self._eval_every_s):
                return self._cached
            self._prune_locked(now)
            objectives: Dict[str, Any] = {}
            for name, target in self.objectives.items():
                kind, _ = OBJECTIVE_KINDS[name]
                vf = self._value(name, self.fast_window_s, now)
                vs = self._value(name, self.slow_window_s, now)
                bf = self._burn(name, vf)
                bs = self._burn(name, vs)
                verdict = self._verdict_locked(name, bf, bs)
                prev = self._state[name]
                if verdict == "BREACH" and prev != "BREACH":
                    self.breaches_total += 1
                    self._transitions.append(
                        {"edge": "breach", "objective": name, "t": now,
                         "burn_rate_fast": round(bf, 4),
                         "value_fast": vf, "target": target})
                elif prev == "BREACH" and verdict != "BREACH":
                    self._transitions.append(
                        {"edge": "recovered", "objective": name,
                         "t": now, "burn_rate_fast": round(bf, 4),
                         "value_fast": vf, "target": target})
                self._state[name] = verdict
                objectives[name] = {
                    "target": target, "kind": kind, "verdict": verdict,
                    "value_fast": vf, "value_slow": vs,
                    "burn_rate_fast": round(bf, 4),
                    "burn_rate_slow": round(bs, 4),
                }
            self._cached = {
                "verdict": worst_verdict(
                    [o["verdict"] for o in objectives.values()]),
                "objectives": objectives,
                "breaches_total": self.breaches_total,
                "windows": {"fast_s": self.fast_window_s,
                            "slow_s": self.slow_window_s},
            }
            self._cached_at = now
            return self._cached

    def pop_transitions(self) -> List[Dict[str, Any]]:
        """Drain the breach/recover edges recorded since the last call
        — each edge is returned exactly once, so trace events and
        breach counters fire once per transition, not per poll."""
        with self._lock:
            out, self._transitions = self._transitions, []
            return out


def rollup(slo_dicts: Sequence[Optional[Dict[str, Any]]]
           ) -> Dict[str, Any]:
    """Fleet-wide aggregation of per-replica `SloTracker.evaluate()`
    dicts (the Router's view): worst-of verdict overall and per
    objective, max burn rates (the hottest replica defines the fleet's
    burn), summed lifetime breach counts. Replicas with SLO tracking
    off (None entries) are skipped; an empty fleet reports OK."""
    live = [d for d in slo_dicts if d]
    objectives: Dict[str, Any] = {}
    for d in live:
        for name, o in d.get("objectives", {}).items():
            cur = objectives.get(name)
            if cur is None:
                objectives[name] = dict(o)
                continue
            cur["verdict"] = worst_verdict([cur["verdict"],
                                            o["verdict"]])
            for k in ("burn_rate_fast", "burn_rate_slow"):
                cur[k] = max(cur[k], o[k])
    return {
        "verdict": worst_verdict(
            [d.get("verdict", "OK") for d in live]),
        "objectives": objectives,
        "breaches_total": sum(d.get("breaches_total", 0) for d in live),
        "replicas_reporting": len(live),
    }
