"""paddle_tpu.serving.cache — automatic prefix cache over shared KV blocks.

Reference analog: vLLM-style automatic prefix caching (the Ragged Paged
Attention serving stack, PAPERS.md): requests that share a prompt prefix
share the KV *blocks* holding that prefix instead of re-prefilling from
token zero. The TPU paged layout makes this free on the device side —
the block table is already an indirection, so sharing is purely a
host-side bookkeeping change: the same pool block id appears in several
requests' table rows.

Two host-side pieces cooperate:

  * `PrefixCacheIndex` (here) — a trie over FULL-block token contents
    mapping a prompt prefix to the chain of pool block ids that already
    hold its KV. Match granularity is a whole block: a block is
    shareable only once every one of its `block_size` positions is
    written, so the partially-filled tail of a prompt is never shared
    (see the copy-on-write rule in `ContinuousBatcher._admit_one`).
  * `RefcountingBlockAllocator` (`paddle_tpu.nlp.paged`) — per-block
    refcounts plus an LRU list of refcount-0 *cached* blocks whose KV is
    preserved for future hits until pool pressure evicts them; eviction
    calls back into `PrefixCacheIndex.evict` so the index never points
    at a reclaimed block.

Single-writer discipline: like the `ContinuousBatcher` that owns it, the
index is only ever touched from the engine thread — no locks here, by
design (LOCK001 stays silent because there is nothing to mis-order).

Keys are exact token tuples, not hashes of them: a trie edge stores the
block's full token content, so a "hash collision" cannot alias two
different prefixes to the same KV (the usual content-hash scheme needs a
verify step; the exact-key trie IS the verify step).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixCacheIndex"]


class _TrieNode:
    """One full block of a cached prefix chain: `key` is the block's
    token tuple, `block` the pool block id holding its KV, `children`
    the continuation edges, `parent` the children-dict this node lives
    in (so eviction can unlink without a root walk)."""

    __slots__ = ("key", "block", "children", "parent")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Dict[Tuple[int, ...], "_TrieNode"]):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], _TrieNode] = {}


class PrefixCacheIndex:
    """Trie over full-block token contents → cached KV block-id chains.

    `match(tokens)` returns the longest chain of pool block ids whose
    recorded contents equal the prompt's leading full blocks;
    `insert(tokens, blocks)` registers a request's full blocks at
    admission (prompt) and retirement (prompt + generated), and
    `evict(block)` unlinks a block the allocator reclaimed. The caller
    (ContinuousBatcher) owns refcounts — the index never frees anything.
    """

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = int(block_size)
        self._children: Dict[Tuple[int, ...], _TrieNode] = {}  # trie root
        self._by_block: Dict[int, _TrieNode] = {}
        # admission-observed stats (the serving metrics surface)
        self.hits = 0                 # admissions with cached_tokens > 0
        self.misses = 0               # admissions served fully cold
        self.hit_tokens = 0           # prefill tokens skipped (saved)
        self.prompt_tokens = 0        # prefill tokens requested in total
        self.inserted_blocks = 0
        self.evicted_blocks = 0

    def __len__(self) -> int:
        return len(self._by_block)

    def match(self, tokens: Sequence[int]) -> List[int]:
        """Longest cached chain for this prompt: pool block ids holding
        tokens[0:block_size], tokens[block_size:2*block_size], ... Reads
        only — refcount bumps (`share`) are the caller's move."""
        bs = self.block_size
        out: List[int] = []
        children = self._children
        for i in range(len(tokens) // bs):
            node = children.get(tuple(tokens[i * bs:(i + 1) * bs]))
            if node is None:
                break
            out.append(node.block)
            children = node.children
        return out

    def insert(self, tokens: Sequence[int],
               blocks: Sequence[int]) -> List[int]:
        """Register a chain of FULL blocks (len(tokens) must equal
        len(blocks) * block_size, block i holding tokens[i*bs:(i+1)*bs]).
        When a prefix node already exists its incumbent block id is kept
        (the newcomer's block simply stays uncached — first writer wins,
        so concurrent identical prompts converge on one chain). Returns
        the block ids newly added to the index; the caller must
        `mark_cached` them on its allocator."""
        bs = self.block_size
        if len(tokens) != len(blocks) * bs:
            raise ValueError(
                f"insert(): {len(tokens)} tokens is not "
                f"{len(blocks)} full blocks of {bs}")
        new: List[int] = []
        children = self._children
        for i, blk in enumerate(blocks):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            node = children.get(key)
            if node is None:
                node = _TrieNode(key, int(blk), children)
                children[key] = node
                self._by_block[int(blk)] = node
                new.append(int(blk))
                self.inserted_blocks += 1
            children = node.children
        return new

    def evict(self, block: int) -> None:
        """Unlink the node holding `block` (allocator eviction callback).
        Descendant nodes become unreachable from the root — matches stop
        at the hole — but stay registered in the block map so their own
        eviction (they are older in the allocator's LRU or still live)
        cleans them up; memory stays bounded by the pool size."""
        if self.unlink(block):
            self.evicted_blocks += 1

    def unlink(self, block: int) -> bool:
        """Remove `block` from the index WITHOUT counting an eviction —
        the admission-rollback path undoes registrations whose KV was
        never written, which is not pool pressure and must not show up
        as `evicted_blocks` on the metrics surface. Returns True when
        the block was indexed."""
        node = self._by_block.pop(block, None)
        if node is None:
            return False
        if node.parent.get(node.key) is node:
            del node.parent[node.key]
        return True

    def note_admission(self, prompt_len: int, cached_tokens: int) -> None:
        """Record one admission's hit accounting (called by the batcher
        with the prefix length it actually reused)."""
        self.prompt_tokens += int(prompt_len)
        self.hit_tokens += int(cached_tokens)
        if cached_tokens > 0:
            self.hits += 1
        else:
            self.misses += 1

    @property
    def hit_rate(self) -> float:
        """Fraction of requested prefill tokens served from cache."""
        return self.hit_tokens / self.prompt_tokens \
            if self.prompt_tokens else 0.0

    def stats(self) -> Dict[str, float]:
        """Plain-dict counters for the serving metrics snapshot."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_tokens": self.hit_tokens,
            "prompt_tokens": self.prompt_tokens,
            "hit_rate": round(self.hit_rate, 6),
            "indexed_blocks": len(self._by_block),
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
        }
