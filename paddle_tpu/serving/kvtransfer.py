"""Portable KV-block snapshots — the transfer primitive behind
disaggregated prefill/decode serving (ROADMAP direction 2).

A `KVSnapshot` is a dependency-free host container holding ONE
request's paged-KV state: the per-layer block contents for exactly the
blocks the request's chain owns (gathered in one coalesced device_get
— never the whole pool), the int8 scale-pool entries for those same
blocks when the source batcher quantizes its KV, the token ids that
produced them, and a model-shape fingerprint so an import into an
incompatible batcher fails fast instead of producing garbage KV.

Three consumers share this one primitive:

- **Disaggregation** — a prefill-role `ServingEngine` finishes a
  request at prefill-complete and surrenders its snapshot; the Router
  migrates it to a decode replica which resumes decoding with ZERO
  prefill chunks (`ContinuousBatcher.import_kv`).
- **Failover / quarantine** — when the failed device call committed
  nothing, innocents' KV is exported before their slots are torn down
  and re-imported (same engine for quarantine, a survivor replica for
  failover) instead of re-prefilled from `prompt + tokens`.
- **Supervisor respawn** — `ReplicaSupervisor` drains-and-exports a
  slot's active requests before teardown so the respawned engine
  resumes them warm.

The snapshot is deliberately host-side and framework-free (numpy
arrays + plain ints): it can cross process/wire boundaries by pickling
today, and the block-granular layout is the natural unit for an
RDMA/ICI transport later (recorded follow-on). This module imports
neither jax nor paddle_tpu — the batcher owns the device side.

Snapshots are MESH-AGNOSTIC: a tensor-parallel batcher's
`export_kv` device_get gathers the sharded pool into full host
arrays (every kv head, not one shard), and `import_kv`'s eager
scatter onto a committed sharded pool re-distributes them — so the
fingerprint deliberately excludes mesh layout, and a snapshot
exported at TP=2 resumes bit-identically on a single-device or TP=4
replica (serving.tp; covered in tests/test_tp_serving.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["KVSnapshot", "check_compatible"]

#: fingerprint keys that must match bit-for-bit between the exporting
#: and importing batcher — each guards a distinct way an import could
#: silently corrupt the destination pool (shape mismatch, code/scale
#: misinterpretation, block-boundary drift).
FINGERPRINT_KEYS = (
    "num_layers", "num_key_value_heads", "head_dim",
    "block_size", "kv_dtype", "pool_dtype",
)


@dataclass
class KVSnapshot:
    """One request's portable paged-KV state.

    `k`/`v` are `[L, n_blocks, block_size, KV_heads, head_dim]` host
    arrays — the pool's own storage dtype (codes, for an int8 pool),
    gathered in chain order so block i holds tokens
    `[i*block_size, (i+1)*block_size)`. `k_scale`/`v_scale` are the
    matching `[L, n_blocks]` float32 scale-pool entries (None for an
    fp pool); transferring them verbatim keeps the grow-only sentinel
    discipline intact — a 0.0 entry stays "never written".

    `tokens` is the full sequence `prompt + generated`, INCLUDING the
    last emitted token whose KV was never written (decode writes token
    t's KV while producing t+1) — so the written KV length is
    `len(tokens) - 1` and the import resumes decode AT `len(tokens)`.
    `tail_valid` records how many positions of the final block hold
    real KV (`block_size` when the written length is block-aligned).
    """
    k: Any                               # [L, n, bs, KV, hd] host array
    v: Any                               # [L, n, bs, KV, hd] host array
    k_scale: Optional[Any]               # [L, n] f32, or None (fp pool)
    v_scale: Optional[Any]               # [L, n] f32, or None (fp pool)
    tokens: List[int]                    # prompt + generated (see above)
    prompt_len: int                      # len(prompt) within `tokens`
    budget: int                          # remaining emission budget
    stop_token_id: int                   # per-request stop id (-1 = none)
    tail_valid: int                      # valid positions in final block
    fingerprint: Dict[str, Any]          # model/pool-shape compatibility
    src_blocks: List[int] = field(default_factory=list)
    src_replica: str = ""                # exporting replica's id

    @property
    def n_blocks(self) -> int:
        """Blocks this snapshot carries (the chain's written extent)."""
        return int(self.k.shape[1])

    @property
    def nbytes(self) -> int:
        """Host bytes of KV payload (codes + scales) — what a wire
        transport would move; token ids and metadata are noise next
        to it and are not counted."""
        n = int(self.k.nbytes) + int(self.v.nbytes)
        if self.k_scale is not None:
            n += int(self.k_scale.nbytes)
        if self.v_scale is not None:
            n += int(self.v_scale.nbytes)
        return n

    def describe(self) -> Dict[str, Any]:
        """Plain-dict summary for traces/logs (no array payloads)."""
        return {
            "blocks": self.n_blocks, "bytes": self.nbytes,
            "tokens": len(self.tokens), "prompt_len": self.prompt_len,
            "budget": self.budget, "tail_valid": self.tail_valid,
            "kv_dtype": self.fingerprint.get("kv_dtype"),
            "src_replica": self.src_replica,
        }


def check_compatible(snapshot_fp: Dict[str, Any],
                     local_fp: Dict[str, Any]) -> List[str]:
    """Compare a snapshot's fingerprint against the importing batcher's
    — returns a list of human-readable mismatches (empty = compatible).
    The import path raises ValueError listing these, so a topology
    mistake (wrong model, wrong kv_dtype, different block size) fails
    at the handoff boundary, not as silent KV corruption."""
    problems = []
    for key in FINGERPRINT_KEYS:
        a, b = snapshot_fp.get(key), local_fp.get(key)
        if a != b:
            problems.append(f"{key}: snapshot={a!r} local={b!r}")
    return problems
