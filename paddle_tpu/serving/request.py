"""paddle_tpu.serving.request — request lifecycle + per-request channel.

One `GenerationRequest` is the unit the engine schedules: it carries the
prompt and decode config in, and tokens out through a thread-safe
channel that supports both blocking (`result()`) and incremental
(`stream()`) consumption.

State machine (engine-thread writes, any thread reads):

    QUEUED -> PREFILL -> DECODING -> FINISHED
                 \\          |\\---> CANCELLED   (consumer called cancel())
                  \\         +----> TIMED_OUT   (deadline passed)
                   +-------------> FAILED      (this request's step or
                                                on_token callback raised)

QUEUED can jump straight to CANCELLED / TIMED_OUT / FAILED (reaped
before admission). Terminal states free the request's KV blocks back to
the pool and close the channel. One loop exists off the happy path:
the engine's quarantine may requeue an in-flight request after a step
failure, re-entering PREFILL from PREFILL or DECODING — the request
resumes from `prompt + tokens`, so the channel only ever sees each
token once.
"""
from __future__ import annotations

import enum
import queue
import threading
from typing import Callable, Iterator, List, Optional

__all__ = [
    "GenerationRequest", "RequestState", "TERMINAL_STATES",
    "RequestError", "RequestCancelled", "RequestFailed", "RequestTimedOut",
]


class RequestState(enum.Enum):
    """Lifecycle states of a GenerationRequest (see module docstring
    for the transition diagram)."""

    QUEUED = "QUEUED"
    PREFILL = "PREFILL"
    DECODING = "DECODING"
    FINISHED = "FINISHED"
    CANCELLED = "CANCELLED"
    FAILED = "FAILED"
    TIMED_OUT = "TIMED_OUT"


TERMINAL_STATES = frozenset({
    RequestState.FINISHED, RequestState.CANCELLED,
    RequestState.FAILED, RequestState.TIMED_OUT,
})


class RequestError(RuntimeError):
    """A request ended in a non-FINISHED terminal state."""

    def __init__(self, request: "GenerationRequest", msg: str):
        super().__init__(msg)
        self.request = request


class RequestCancelled(RequestError):
    """result()/stream() on a request that was cancel()ed."""


class RequestTimedOut(RequestError):
    """result()/stream() on a request whose deadline expired."""


class RequestFailed(RequestError):
    """result()/stream() on a request whose decode step or on_token
    callback raised (the original error is on `.request.error`)."""


_SENTINEL = object()      # channel close marker


class GenerationRequest:
    """One generation request.

    Consumer-side API: `cancel()`, `result(timeout)`, `stream()`,
    `wait(timeout)`, `done`. Everything `_`-prefixed is engine-side and
    must only be called from the engine thread.

    `priority`: smaller = served sooner (FIFO among equals, with aging —
    see scheduler.AdmissionQueue). `max_new_tokens` None means "the
    engine's max" — ServingEngine.submit() resolves it in place.
    `timeout_s` is a wall-clock deadline from submission covering queue
    wait AND decode. `stop_token_id` finishes the request early when
    emitted (per-request — rides the ContinuousBatcher's per-slot stop
    support). `on_token` is called in the engine thread per generated
    token; if it raises, only THIS request fails (the engine's
    exception boundary).

    Fault tolerance: `retries` counts backoff re-admissions the
    engine's quarantine granted this request as a transient-failure
    culprit (victims of SOMEONE ELSE'S fault are requeued without
    consuming it). A re-admitted request resumes from
    `prompt + tokens` — already-streamed tokens are never re-emitted
    or lost — and `request_id` moves to the new batcher rid."""

    def __init__(self, prompt, *, priority: int = 0,
                 max_new_tokens: Optional[int] = None,
                 stop_token_id: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 on_token: Optional[Callable[[int], None]] = None):
        self.prompt: List[int] = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.priority = int(priority)
        self.max_new_tokens = (None if max_new_tokens is None
                               else int(max_new_tokens))
        self.stop_token_id = (None if stop_token_id is None
                              else int(stop_token_id))
        self.timeout_s = timeout_s
        self.on_token = on_token

        self.state = RequestState.QUEUED
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.finish_reason: Optional[str] = None
        self.retries = 0          # transient-culprit re-admissions used
        # quarantine's plain-decode fallback: set when this request
        # rode a FAILED speculative tick — its re-admissions opt out
        # of the spec pipeline (the convicted spec step must not get a
        # second chance to poison the same request's recovery)
        self.spec_opt_out = False
        # portable KV attached at a handoff boundary
        # (serving.kvtransfer.KVSnapshot, or None): a prefill-role
        # engine surrenders the request's KV here at "prefill_complete"
        # and a failing engine attaches it on the way down — the Router
        # imports it at the destination instead of re-prefilling,
        # falling back to warm re-prefill when it is None
        self.kv_snapshot = None

        # engine-stamped timeline (engine clock, typically time.monotonic)
        self.request_id: Optional[int] = None       # batcher rid once admitted
        self.submit_time: Optional[float] = None
        self.deadline: Optional[float] = None
        self.admit_time: Optional[float] = None
        self.first_token_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.admitted_index: Optional[int] = None   # global admission order
        self.trace_id: Optional[str] = None         # serving.trace timeline

        self._cancel = threading.Event()
        self._done = threading.Event()
        self._chan: "queue.Queue" = queue.Queue()

    # ---- consumer side ---------------------------------------------------
    def cancel(self) -> None:
        """Request cancellation; the engine honors it at its next
        scheduling point (queued: before admission; decoding: between
        chunks, freeing the KV blocks)."""
        self._cancel.set()

    @property
    def cancel_requested(self) -> bool:
        return self._cancel.is_set()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until terminal; True if the request reached a terminal
        state within `timeout`."""
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until finished and return the generated tokens.
        Raises RequestCancelled / RequestTimedOut / RequestFailed when
        the request did not FINISH (partial tokens stay readable on
        `.tokens`); TimeoutError when `timeout` expires first."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request not finished within {timeout}s "
                f"(state={self.state.name})")
        if self.state is RequestState.FINISHED:
            return list(self.tokens)
        exc = {RequestState.CANCELLED: RequestCancelled,
               RequestState.TIMED_OUT: RequestTimedOut}.get(
                   self.state, RequestFailed)
        raise exc(self, f"request ended {self.state.name}"
                        f"{f': {self.error!r}' if self.error else ''}")

    def stream(self) -> Iterator[int]:
        """Yield tokens as the engine generates them (one live consumer
        at a time). Ends cleanly on FINISHED or CANCELLED; raises
        RequestTimedOut / RequestFailed so a consumer can't mistake a
        truncated stream for a complete one. Safe to call again after
        the request is terminal (yields nothing instead of blocking on
        the already-consumed close sentinel)."""
        while True:
            if self._done.is_set():
                # _finish enqueues the sentinel BEFORE setting done, so
                # once done a non-blocking drain sees every remaining
                # token — never block on a channel that may already be
                # fully consumed (repeat stream() call)
                try:
                    t = self._chan.get_nowait()
                except queue.Empty:
                    break
            else:
                t = self._chan.get()
            if t is _SENTINEL:
                break
            yield t
        if self.state is RequestState.TIMED_OUT:
            raise RequestTimedOut(self, "request timed out mid-stream")
        if self.state is RequestState.FAILED:
            raise RequestFailed(self, f"request failed: {self.error!r}")

    # ---- engine side -----------------------------------------------------
    def _deliver(self, tok: int) -> None:
        self.tokens.append(tok)
        if self.state is RequestState.PREFILL:
            self.state = RequestState.DECODING
        self._chan.put(tok)

    def _finish(self, state: RequestState, reason: Optional[str] = None,
                error: Optional[BaseException] = None,
                now: Optional[float] = None) -> None:
        if self.done:
            return
        self.state = state
        self.finish_reason = reason or state.name.lower()
        self.error = error
        self.finish_time = now
        self._chan.put(_SENTINEL)
        self._done.set()

    def __repr__(self) -> str:
        return (f"GenerationRequest(id={self.request_id}, "
                f"state={self.state.name}, prio={self.priority}, "
                f"prompt_len={len(self.prompt)}, "
                f"tokens={len(self.tokens)})")
