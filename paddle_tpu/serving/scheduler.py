"""paddle_tpu.serving.scheduler — admission control for the engine.

Reference analog: the serving frontends over continuous batchers
(PaddleNLP serving / vLLM-style schedulers) keep a bounded priority
queue in front of the device batch: admission order is
priority-then-FIFO, a full queue REJECTS (backpressure to the client
instead of buffering until OOM), and waiting requests age so a stream of
high-priority arrivals cannot starve the tail.

Block-aware deferral reuses the ContinuousBatcher's defer-on-no-blocks
logic: `pop(fits=...)` hands out the best request only when its KV-block
need fits the pool right now, and otherwise defers the WHOLE queue
(head-of-line) — skipping ahead to smaller requests would starve big
ones forever, and the engine has already validated at submit time that
every queued request fits an empty pool, so deferral always resolves.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, NamedTuple, Optional

__all__ = ["AdmissionQueue", "QueueFullError"]


class QueueFullError(RuntimeError):
    """Queue at max_depth — the caller should retry later or shed load."""


class _Entry(NamedTuple):
    priority: int
    seq: int
    enq_time: float
    item: object


class AdmissionQueue:
    """Bounded priority queue: smaller priority first, FIFO within a
    priority, starvation-free aging.

    Aging: an entry's effective priority improves by one level per
    `aging_interval_s` waited, so a priority-9 request that has waited
    9 intervals competes with fresh priority-0 traffic. Ties (same
    effective priority) break by submission order."""

    # requeued items outrank every real priority level; aging can only
    # make real priorities SMALLER over time, but never by anywhere
    # near this much (2^30 aging intervals), so front entries stay in
    # front without freezing the aging math
    _FRONT_PRIORITY = -(1 << 30)

    def __init__(self, max_depth: int = 256,
                 aging_interval_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        self.max_depth = max_depth
        self.aging_interval_s = float(aging_interval_s)
        self._clock = clock
        self._items: List[_Entry] = []
        self._seq = 0
        self._front = 0        # decreasing seqs for front-requeued items
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def push(self, item, priority: int = 0) -> None:
        with self._lock:
            if len(self._items) >= self.max_depth:
                raise QueueFullError(
                    f"admission queue full ({self.max_depth} requests "
                    f"waiting) — rejecting instead of buffering")
            self._items.append(
                _Entry(int(priority), self._seq, self._clock(), item))
            self._seq += 1

    def _key(self, e: _Entry, now: float,
             prefer: Optional[Callable[[object], bool]] = None):
        aged = int((now - e.enq_time) / self.aging_interval_s) \
            if self.aging_interval_s > 0 else 0
        if prefer is None:
            return (e.priority - aged, e.seq)
        # preference is a TIE-BREAK within an effective-priority level:
        # it can reorder equals (cache-aware admission) but never jump
        # a lower-priority request over a higher one
        return (e.priority - aged, 0 if prefer(e.item) else 1, e.seq)

    def pop(self, fits: Optional[Callable[[object], bool]] = None,
            prefer: Optional[Callable[[object], bool]] = None):
        """Remove and return the best (aged-priority, FIFO) item.

        With `fits`, the best item is returned only when fits(item) is
        True; otherwise the queue DEFERS as a whole (returns None) —
        the batcher's defer-on-no-blocks semantics. With `prefer`, items
        for which prefer(item) is True win ties WITHIN an effective
        priority level (the engine passes cached-prefix preference, so
        reclaimable KV is reused before eviction recycles it); FIFO
        still breaks remaining ties. Returns None when empty."""
        got = self.pop_many(1, fits=fits, prefer=prefer)
        return got[0] if got else None

    def pop_many(self, k: int,
                 fits: Optional[Callable[[object], bool]] = None,
                 prefer: Optional[Callable[[object], bool]] = None
                 ) -> List[object]:
        """Pop up to `k` best items under ONE lock acquisition and one
        consistent clock reading — the engine's admission round takes a
        whole burst at once instead of re-locking per request (the burst
        then prefills in one compiled call batcher-side). Same
        semantics as `pop` applied repeatedly: head-of-line deferral
        (the best REMAINING item failing `fits` stops the round),
        `prefer` tie-breaks within an effective-priority level. `fits`
        is called once per accepted item, so callers may account
        resources (KV blocks) inside it."""
        out: List[object] = []
        with self._lock:
            now = self._clock()
            while len(out) < k and self._items:
                best = min(self._items,
                           key=lambda e: self._key(e, now, prefer))
                if fits is not None and not fits(best.item):
                    break
                self._items.remove(best)
                out.append(best.item)
        return out

    def requeue(self, items) -> None:
        """Insert `items` at the FRONT of the queue — before every
        waiting request at any priority, preserving the given order
        among themselves (a later requeue batch goes in front of an
        earlier one). The engine's quarantine/retry paths use this to
        re-admit recovered in-flight work before fresh traffic, so a
        step failure costs the victims one re-prefill, not a trip to
        the back of the line. Deliberately exempt from `max_depth`:
        these items already held admission once, and bouncing them on
        backpressure would turn recovery into data loss."""
        with self._lock:
            now = self._clock()
            for item in reversed(list(items)):
                self._front -= 1
                self._items.append(_Entry(self._FRONT_PRIORITY,
                                          self._front, now, item))

    def peek(self):
        """The item pop() would consider next (no removal)."""
        with self._lock:
            if not self._items:
                return None
            now = self._clock()
            return min(self._items, key=lambda e: self._key(e, now)).item

    def reap(self, predicate: Callable[[object], bool]) -> List[object]:
        """Remove and return every item matching `predicate` (used for
        cancellation and deadline expiry of still-queued requests)."""
        with self._lock:
            hit = [e for e in self._items if predicate(e.item)]
            for e in hit:
                self._items.remove(e)
            return [e.item for e in hit]

    def clear(self) -> List[object]:
        with self._lock:
            items = [e.item for e in self._items]
            self._items.clear()
            return items
